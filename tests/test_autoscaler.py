"""Autoscaler: scale up on unmet demand, scale down on idle timeout
(reference: autoscaler/v2/autoscaler.py + fake_multinode provider)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeTypeConfig
from ray_tpu.cluster_utils import Cluster


def test_scales_up_then_down():
    c = Cluster(head_num_cpus=1, max_workers=1)
    provider = LocalNodeProvider(c)
    scaler = Autoscaler(
        provider,
        [NodeTypeConfig("cpu-worker", {"CPU": 2}, min_workers=0, max_workers=3)],
        poll_interval_s=0.2,
        upscale_delay_s=0.2,
        idle_timeout_s=2.0,
    ).start()
    try:
        @ray_tpu.remote(num_cpus=2)  # cannot fit on the 1-CPU head
        def big_task(i):
            time.sleep(1.0)
            import os

            return os.environ.get("RAY_TPU_NODE_ID")

        refs = [big_task.remote(i) for i in range(2)]
        nodes = ray_tpu.get(refs, timeout=60)  # only possible post-scale-up
        assert all(n != "node0" for n in nodes)
        assert len(provider.non_terminated_nodes()) >= 1

        # demand gone: idle nodes retire after the timeout
        deadline = time.time() + 30
        while time.time() < deadline and provider.non_terminated_nodes():
            time.sleep(0.3)
        assert provider.non_terminated_nodes() == []
    finally:
        scaler.stop()
        c.shutdown()


def test_quota_parked_demand_does_not_scale_up():
    """fairsched satellite: demand the autoscaler sees is POST-quota —
    work parked by a tenant's admission quota is flagged
    pending_quota and must not buy nodes (no amount of hardware can
    dispatch it)."""
    from ray_tpu import JobConfig
    from ray_tpu._private import worker
    from ray_tpu.autoscaler import NodeProvider

    class RecordingProvider(NodeProvider):
        def __init__(self):
            self.created = []

        def create_node(self, node_type):
            self.created.append(node_type.name)
            return f"fake-{len(self.created)}"

        def terminate_node(self, node_id):
            pass

        def non_terminated_nodes(self):
            return []

    ray_tpu.init(
        num_cpus=1, max_workers=1, ignore_reinit_error=True,
        job_config=JobConfig(tenant="capped", quota={"CPU": 1}),
    )
    try:
        @ray_tpu.remote(num_cpus=1)
        def hold(i):
            time.sleep(1.5)
            return i

        refs = [hold.remote(i) for i in range(4)]  # 1 admitted, 3 parked
        client = worker.get_client()
        # poll for the STABLE window: one task running, every remaining
        # demand row quota-parked. An admitted-but-not-yet-dispatched
        # task transiently shows a plain demand row (at startup and at
        # each 1.5s re-admission boundary) — that's legitimate demand,
        # not a flagging bug, so don't assert on a snapshot inside it.
        deadline = time.time() + 30
        demand = None
        while time.time() < deadline:
            demand = client.list_state("demand")
            running = [
                t for t in client.list_state("tasks")
                if t.get("state") == "RUNNING"
            ]
            if running and demand and all(
                d.get("pending_quota") for d in demand
            ):
                break
            time.sleep(0.1)
        assert demand and all(d.get("pending_quota") for d in demand), demand
        provider = RecordingProvider()
        scaler = Autoscaler(
            provider,
            [NodeTypeConfig("w", {"CPU": 4}, max_workers=3)],
            upscale_delay_s=0.0,
        )
        scaler.step()
        scaler.step()  # second pass: past any upscale delay
        assert provider.created == [], (
            "autoscaler bought nodes for quota-parked demand"
        )
        assert ray_tpu.get(refs, timeout=60) == list(range(4))
    finally:
        ray_tpu.shutdown()


def test_respects_max_workers():
    c = Cluster(head_num_cpus=1, max_workers=1)
    provider = LocalNodeProvider(c)
    scaler = Autoscaler(
        provider,
        [NodeTypeConfig("w", {"CPU": 1}, max_workers=2)],
        poll_interval_s=0.1,
        upscale_delay_s=0.1,
        idle_timeout_s=60.0,
    ).start()
    try:
        @ray_tpu.remote(num_cpus=1)
        def hold(i):
            time.sleep(3)
            return i

        # far more demand than max_workers allows
        refs = [hold.remote(i) for i in range(8)]
        time.sleep(2.0)
        assert len(provider.non_terminated_nodes()) <= 2
        ray_tpu.get(refs, timeout=120)
    finally:
        scaler.stop()
        c.shutdown()
