"""Autoscaler: scale up on unmet demand, scale down on idle timeout
(reference: autoscaler/v2/autoscaler.py + fake_multinode provider)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeTypeConfig
from ray_tpu.cluster_utils import Cluster


def test_scales_up_then_down():
    c = Cluster(head_num_cpus=1, max_workers=1)
    provider = LocalNodeProvider(c)
    scaler = Autoscaler(
        provider,
        [NodeTypeConfig("cpu-worker", {"CPU": 2}, min_workers=0, max_workers=3)],
        poll_interval_s=0.2,
        upscale_delay_s=0.2,
        idle_timeout_s=2.0,
    ).start()
    try:
        @ray_tpu.remote(num_cpus=2)  # cannot fit on the 1-CPU head
        def big_task(i):
            time.sleep(1.0)
            import os

            return os.environ.get("RAY_TPU_NODE_ID")

        refs = [big_task.remote(i) for i in range(2)]
        nodes = ray_tpu.get(refs, timeout=60)  # only possible post-scale-up
        assert all(n != "node0" for n in nodes)
        assert len(provider.non_terminated_nodes()) >= 1

        # demand gone: idle nodes retire after the timeout
        deadline = time.time() + 30
        while time.time() < deadline and provider.non_terminated_nodes():
            time.sleep(0.3)
        assert provider.non_terminated_nodes() == []
    finally:
        scaler.stop()
        c.shutdown()


def test_respects_max_workers():
    c = Cluster(head_num_cpus=1, max_workers=1)
    provider = LocalNodeProvider(c)
    scaler = Autoscaler(
        provider,
        [NodeTypeConfig("w", {"CPU": 1}, max_workers=2)],
        poll_interval_s=0.1,
        upscale_delay_s=0.1,
        idle_timeout_s=60.0,
    ).start()
    try:
        @ray_tpu.remote(num_cpus=1)
        def hold(i):
            time.sleep(3)
            return i

        # far more demand than max_workers allows
        refs = [hold.remote(i) for i in range(8)]
        time.sleep(2.0)
        assert len(provider.non_terminated_nodes()) <= 2
        ray_tpu.get(refs, timeout=120)
    finally:
        scaler.stop()
        c.shutdown()
