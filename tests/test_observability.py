"""Observability floor: metrics registry (util.metrics), task events
feeding list_state, chrome-trace timeline, ds.stats() per-op wall
times, and serve streaming responses."""

import time

import pytest

import ray_tpu
from ray_tpu.util import metrics


def _wait_for(cond, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_counter_gauge_histogram(ray_start_regular):
    c = metrics.Counter("req_total", description="requests", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(5, tags={"route": "/b"})
    g = metrics.Gauge("inflight")
    g.set(7)
    h = metrics.Histogram("latency_s", boundaries=[0.1, 1.0, 10.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(99.0)

    def ready():
        snap = {(m["name"], m["tags"]): m for m in metrics.snapshot()}
        return (
            snap.get(("req_total", (("route", "/a"),)), {}).get("value") == 3.0
            and snap.get(("inflight", ()), {}).get("value") == 7.0
            and snap.get(("latency_s", ()), {}).get("count") == 3
        )

    assert _wait_for(ready), metrics.snapshot()
    snap = {(m["name"], m["tags"]): m for m in metrics.snapshot()}
    hist = snap[("latency_s", ())]
    assert hist["sum"] == pytest.approx(99.55)
    assert hist["buckets"] == [[0.1, 1], [1.0, 1], [10.0, 0]]
    text = metrics.prometheus_text()
    assert 'req_total{route="/a"} 3.0' in text
    assert "# TYPE latency_s histogram" in text


def test_task_events_and_timeline(ray_start_regular):
    @ray_tpu.remote
    def traced():
        time.sleep(0.05)
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])

    client = ray_tpu._private.worker.get_client()

    def done():
        evs = client.list_state("tasks")
        fin = [e for e in evs if e.get("state") == "FINISHED"]
        return len(fin) >= 3

    assert _wait_for(done)
    evs = client.list_state("tasks")
    ev = [e for e in evs if e.get("state") == "FINISHED"][0]
    assert ev["finished_at"] >= ev["started_at"] >= ev["submitted_at"]
    assert ev["worker_id"] and ev["node_id"] == "node0"

    trace = ray_tpu.timeline()
    assert trace and all(t["ph"] == "X" for t in trace)
    spans = [t for t in trace if t["dur"] >= 50_000]  # >= 50ms in usecs
    assert spans, trace

    import json
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", mode="r+") as f:
        ray_tpu.timeline(filename=f.name)
        assert json.load(f)


def test_failed_task_event(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise RuntimeError("x")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())
    client = ray_tpu._private.worker.get_client()
    assert _wait_for(
        lambda: any(
            e.get("state") == "FAILED" for e in client.list_state("tasks")
        )
    )


def test_ds_stats(ray_start_regular):
    import ray_tpu.data as rdata

    ds = rdata.range(100).map_batches(lambda b: b).materialize()
    s = ds.stats()
    assert "self" in s and "blocks" in s and "total:" in s


@pytest.fixture
def serve_cleanup(ray_start_4_cpus):
    from ray_tpu import serve

    yield
    serve.shutdown()


def test_serve_streaming_response(serve_cleanup):
    from ray_tpu import serve

    @serve.deployment
    class Tokens:
        def generate(self, n):
            for i in range(n):
                yield f"tok{i} "

    h = serve.run(Tokens.bind())
    out = list(h.options(method_name="generate", stream=True).remote(4))
    assert out == ["tok0 ", "tok1 ", "tok2 ", "tok3 "]


def test_tracing_spans_in_timeline(ray_start_regular):
    """User spans (util/tracing) land in the chrome-trace timeline,
    nested via trace/parent ids, including spans from workers
    (reference: ray.util.tracing opentelemetry hook)."""
    import time

    import ray_tpu
    from ray_tpu.util import tracing

    tracing.enable()
    try:
        with tracing.span("outer", stage="fit") as outer_ctx:
            with tracing.span("inner"):
                time.sleep(0.01)
            ctx = tracing.current_context()
            assert ctx is not None and ctx[0] == outer_ctx[0]

            @ray_tpu.remote
            def work(parent_ctx):
                from ray_tpu.util import tracing as t

                t.enable()
                with t.context(parent_ctx), t.span("remote-stage"):
                    return 7

            assert ray_tpu.get(work.remote(ctx)) == 7
        deadline = time.monotonic() + 10
        names = set()
        while time.monotonic() < deadline:
            events = ray_tpu.timeline()
            names = {e["name"] for e in events if e.get("cat") == "span"}
            if {"outer", "inner", "remote-stage"} <= names:
                break
            time.sleep(0.1)
        assert {"outer", "inner", "remote-stage"} <= names, names
        spans = {e["name"]: e for e in events if e.get("cat") == "span"}
        assert spans["inner"]["args"]["parent_id"] == spans["outer"]["args"]["span_id"]
        assert spans["remote-stage"]["args"]["trace_id"] == spans["outer"]["args"]["trace_id"]
        assert spans["remote-stage"]["args"]["parent_id"] == spans["outer"]["args"]["span_id"]
    finally:
        tracing.disable()
