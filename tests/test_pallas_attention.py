"""Numerical equivalence of the Pallas flash-attention kernel against
the XLA blockwise reference (ops.attention) and against naive softmax
attention — forward and gradients. Runs in Pallas interpret mode on the
CPU mesh; the same kernel compiles via Mosaic on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.pallas_attention import pallas_flash_attention


def _naive(q, k, v, causal=True):
    B, S, H, hd = q.shape
    KVH = k.shape[2]
    G = H // KVH
    qg = q.reshape(B, S, KVH, G, hd).astype(jnp.float32)
    logits = jnp.einsum("bskgh,btkh->bkgst", qg, k.astype(jnp.float32))
    logits /= jnp.sqrt(hd).astype(jnp.float32)
    if causal:
        T = k.shape[1]
        mask = jnp.arange(S)[:, None] >= jnp.arange(T)[None, :]
        logits = jnp.where(mask[None, None, None], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgst,btkh->bskgh", probs, v.astype(jnp.float32))
    return out.reshape(B, S, H, hd).astype(q.dtype)


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=jnp.float32).astype(dtype)


@pytest.mark.parametrize("causal,kvh", [(True, 4), (True, 1), (False, 2)])
def test_forward_matches_reference(causal, kvh):
    B, S, H, hd = 2, 256, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand((B, S, H, hd), ks[0])
    k = _rand((B, S, kvh, hd), ks[1])
    v = _rand((B, S, kvh, hd), ks[2])
    out = pallas_flash_attention(q, k, v, causal, block_q=128, block_kv=128)
    ref = _naive(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    blockwise = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_kv=128)
    np.testing.assert_allclose(out, blockwise, atol=2e-5, rtol=2e-5)


def test_grads_match_reference():
    B, S, H, hd = 1, 256, 4, 128
    kvh = 2
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand((B, S, H, hd), ks[0])
    k = _rand((B, S, kvh, hd), ks[1])
    v = _rand((B, S, kvh, hd), ks[2])

    def loss_pallas(q, k, v):
        o = pallas_flash_attention(q, k, v, True, block_q=128, block_kv=128)
        return jnp.sum(o * jnp.cos(o))

    def loss_naive(q, k, v):
        o = _naive(q, k, v, True)
        return jnp.sum(o * jnp.cos(o))

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gp, gn, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


def test_bf16_close_to_fp32():
    B, S, H, hd = 1, 256, 2, 128
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q32 = _rand((B, S, H, hd), ks[0])
    k32 = _rand((B, S, H, hd), ks[1])
    v32 = _rand((B, S, H, hd), ks[2])
    out16 = pallas_flash_attention(
        q32.astype(jnp.bfloat16), k32.astype(jnp.bfloat16),
        v32.astype(jnp.bfloat16), True, block_q=128, block_kv=128)
    ref = _naive(q32, k32, v32, True)
    assert out16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out16.astype(jnp.float32), ref, atol=4e-2, rtol=4e-2)


def test_rejects_untileable_shapes():
    q = jnp.zeros((1, 256, 2, 64))  # head_dim 64 < lane width
    with pytest.raises(NotImplementedError):
        pallas_flash_attention(q, q, q, True)
    q = jnp.zeros((1, 100, 2, 128))  # seq not a multiple of 128
    with pytest.raises(NotImplementedError):
        pallas_flash_attention(q, q, q, True)


def test_uneven_q_kv_lengths():
    # cross-attention style: T != S (non-causal)
    B, S, T, H, hd = 1, 128, 384, 2, 128
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand((B, S, H, hd), ks[0])
    k = _rand((B, T, H, hd), ks[1])
    v = _rand((B, T, H, hd), ks[2])
    out = pallas_flash_attention(q, k, v, False, block_q=128, block_kv=128)
    ref = _naive(q, k, v, False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
