"""Data preprocessors (reference: python/ray/data/preprocessors/)."""

import numpy as np
import pytest

import ray_tpu.data as rd
from ray_tpu.data.preprocessors import (
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    Preprocessor,
    SimpleImputer,
    StandardScaler,
)


def _items_ds(rows):
    return rd.from_items(rows)


def test_standard_scaler(ray_start_regular):
    ds = _items_ds([{"x": float(i), "y": float(2 * i)} for i in range(10)])
    scaler = StandardScaler(["x", "y"])
    out = scaler.fit_transform(ds)
    xs = np.concatenate([np.asarray(b["x"]) for b in out.iter_batches()])
    assert abs(xs.mean()) < 1e-9
    assert abs(xs.std(ddof=1) - 1.0) < 1e-6
    # transform_batch on a raw dict works too
    b = scaler.transform_batch({"x": np.asarray([4.5]), "y": np.asarray([9.0])})
    assert abs(float(b["x"][0])) < 1e-9  # 4.5 is the mean of 0..9


def test_min_max_scaler(ray_start_regular):
    ds = _items_ds([{"x": float(i)} for i in range(5)])
    out = MinMaxScaler(["x"]).fit_transform(ds)
    xs = sorted(float(r["x"]) for r in out.iter_rows())
    assert xs[0] == 0.0 and xs[-1] == 1.0


def test_one_hot_encoder(ray_start_regular):
    ds = _items_ds([{"c": v} for v in ["a", "b", "a", "c"]])
    enc = OneHotEncoder(["c"]).fit(ds)
    out = enc.transform(ds)
    rows = list(out.iter_rows())
    assert set(rows[0].keys()) == {"c_a", "c_b", "c_c"}
    assert rows[0]["c_a"] == 1 and rows[0]["c_b"] == 0
    totals = {k: sum(r[k] for r in rows) for k in rows[0]}
    assert totals == {"c_a": 2, "c_b": 1, "c_c": 1}


def test_label_encoder_and_unseen(ray_start_regular):
    ds = _items_ds([{"label": v} for v in ["dog", "cat", "dog", "fish"]])
    enc = LabelEncoder("label").fit(ds)
    out = enc.transform(ds)
    labels = [int(r["label"]) for r in out.iter_rows()]
    assert sorted(set(labels)) == [0, 1, 2]
    with pytest.raises(ValueError, match="unseen"):
        enc.transform_batch({"label": np.asarray(["wolf"])})


def test_simple_imputer_mean(ray_start_regular):
    ds = _items_ds([{"x": 1.0}, {"x": float("nan")}, {"x": 3.0}])
    out = SimpleImputer(["x"], strategy="mean").fit_transform(ds)
    xs = sorted(float(r["x"]) for r in out.iter_rows())
    assert xs == [1.0, 2.0, 3.0]


def test_concatenator(ray_start_regular):
    ds = _items_ds([{"a": 1.0, "b": 2.0}, {"a": 3.0, "b": 4.0}])
    out = Concatenator(["a", "b"], output_column_name="feat").fit_transform(ds)
    batches = list(out.iter_batches())
    feat = np.concatenate([np.asarray(b["feat"]) for b in batches])
    assert feat.shape == (2, 2)
    assert feat.dtype == np.float32


def test_chain_scales_then_concats(ray_start_regular):
    ds = _items_ds([{"a": float(i), "b": float(i * 10)} for i in range(8)])
    chain = Chain(StandardScaler(["a", "b"]), Concatenator(["a", "b"]))
    out = chain.fit_transform(ds)
    feat = np.concatenate(
        [np.asarray(b["concat_out"]) for b in out.iter_batches()]
    )
    assert feat.shape == (8, 2)
    assert abs(feat[:, 0].mean()) < 1e-6


def test_unfitted_raises(ray_start_regular):
    with pytest.raises(RuntimeError, match="must be fit"):
        StandardScaler(["x"]).transform_batch({"x": np.asarray([1.0])})
