"""Transparent auto-batching of plain ``.remote()`` calls (client hot
path, round 3): template-spliced SUBMIT_TASKS frames.

Tier-1 coverage for the spliced wire path:
  - splice decode equality: a frame assembled from a cached opcode
    prefix plus hand-emitted per-task fragments decodes exactly like a
    ``dumps_frame`` encoding of the same payload dict;
  - memo-safety: values whose pickle reads the memo (shared refs) are
    rejected at template-build time, falling back to the classic path;
  - burst semantics: a loop of plain ``.remote()`` calls rides the
    batched path, including kwargs and ObjectRef args (arg_deps);
  - fallbacks: num_returns > 1 and ``.options()`` variants stay on the
    classic per-call path — and don't poison the base function;
  - window=0: auto-batching disabled reverts to the per-call
    SUBMIT_TASK frames byte-for-byte (the untouched PR 12 path);
  - singleton degrade: a drain catching exactly one call ships the
    classic SUBMIT_TASK frame (no bulk ack machinery), so sync round
    trips don't pay the batch tax;
  - FIFO: pending auto-batches drain before ANY other outbound message,
    so admission order matches submission order across batch, explicit
    bulk, put, and actor-call boundaries;
  - chaos: dropped and duplicated auto-batch frames recover through the
    REPLY(req_id) ack + raw-bytes retransmit + per-task dedup.
"""

import time

import pytest

import ray_tpu


# --------------------------------------------------------------- splicing


def test_spliced_frame_decodes_like_dumps_frame():
    from ray_tpu._private import protocol as P
    from ray_tpu._private.ids import id_slab
    from ray_tpu._private.serialization import (
        close_submit_frame,
        dumps_frame,
        loads_frame,
        submit_frame_prefix,
        task_entry_fragment,
    )

    fields = {
        "fn_id": "f" * 40,
        "resources": {"CPU": 1.0, "custom": 2.5},
        "options": {"max_retries": 3, "name": "t", "priority": 7},
        "pipeline": False,
    }
    prefix = submit_frame_prefix(P.SUBMIT_TASKS, fields)
    assert prefix is not None

    slab = id_slab(8)
    tasks, frags = [], []
    # payload shapes: short (fast fragment path), >255 B (BINBYTES),
    # empty; middle task also carries an arg dep and two return ids
    for i, pay in enumerate((b"p", b"q" * 300, b"")):
        tid, rid = slab[2 * i], slab[2 * i + 1]
        deps = [slab[6]] if i == 1 else []
        rids = [rid, slab[7]] if i == 1 else [rid]
        frags.append(
            task_entry_fragment(tid, "inline", pay, deps, rids)
        )
        tasks.append({
            "task_id": tid, "args_kind": "inline", "args_payload": pay,
            "arg_deps": deps, "return_ids": rids,
        })

    frame = close_submit_frame(
        prefix, frags, req_id=42, trace=("t" * 16, "s" * 16)
    )
    want = dict(fields)
    want["tasks"] = tasks
    want["req_id"] = 42
    want["trace"] = ("t" * 16, "s" * 16)
    assert loads_frame(frame) == (P.SUBMIT_TASKS, want)
    # ...and both decode identically to the ordinary encoder's output
    assert loads_frame(dumps_frame((P.SUBMIT_TASKS, want))) == (
        P.SUBMIT_TASKS, want
    )


def test_memo_reading_values_are_rejected():
    """A value whose pickle READS the memo (shared reference) cannot be
    spliced into a foreign stream; the template build must refuse it so
    the caller falls back to dumps_frame."""
    from ray_tpu._private.serialization import (
        submit_frame_prefix,
        value_fragment,
    )

    shared = {"a": 1}
    assert value_fragment({"x": shared, "y": shared}) is None
    assert value_fragment({"plain": 1, "ok": "yes"}) is not None
    assert submit_frame_prefix(
        "submit_tasks", {"options": {"x": shared, "y": shared}}
    ) is None


# ------------------------------------------------------------ burst paths


def test_plain_remote_rides_autobatch(ray_start_4_cpus, monkeypatch):
    from ray_tpu._private.client import CoreClient

    batched, singles = [], []
    orig_b = CoreClient.submit_batched
    orig_s = CoreClient.submit_task

    def spy_b(self, *a, **k):
        batched.append(1)
        return orig_b(self, *a, **k)

    def spy_s(self, *a, **k):
        singles.append(1)
        return orig_s(self, *a, **k)

    monkeypatch.setattr(CoreClient, "submit_batched", spy_b)
    monkeypatch.setattr(CoreClient, "submit_task", spy_s)

    @ray_tpu.remote
    def add(a, b=0):
        return a + b

    refs = [add.remote(i) for i in range(100)]
    refs.append(add.remote(1, b=2))  # kwargs ride the batch too
    assert ray_tpu.get(refs, timeout=60) == [*range(100), 3]
    assert len(batched) == 101
    assert not singles


def test_ref_args_through_autobatch(ray_start_4_cpus):
    """ObjectRef args populate arg_deps — the non-fast fragment shape —
    and the hub must still gate execution on the dep."""
    @ray_tpu.remote
    def add(a, b):
        return a + b

    x = ray_tpu.put(10)
    refs = [add.remote(x, i) for i in range(20)]
    assert ray_tpu.get(refs, timeout=60) == [10 + i for i in range(20)]


def test_lone_call_degrades_to_classic_frame(ray_start_4_cpus):
    """A drain that catches exactly ONE buffered call ships the classic
    SUBMIT_TASK frame — same hub handler as the window=0 path, no bulk
    req_id/ack machinery — so a sync .remote()+get() round trip never
    pays the batch ack tax for a batch of one."""
    from ray_tpu._private import protocol as P
    from ray_tpu._private import worker
    from ray_tpu._private.serialization import loads_frame

    @ray_tpu.remote
    def echo(x):
        return x

    assert ray_tpu.get(echo.remote(0)) == 0  # export the function first
    client = worker.get_client()
    assert client._ab_window_s > 0
    frames = []
    orig = client.conn.send_bytes

    def spy(blob):
        frames.append(blob)
        return orig(blob)

    client.conn.send_bytes = spy
    try:
        assert ray_tpu.get(echo.remote(3)) == 3
    finally:
        client.conn.send_bytes = orig
    kinds = [loads_frame(b)[0] for b in frames]
    assert P.SUBMIT_TASK in kinds, kinds
    assert P.SUBMIT_TASKS not in kinds, kinds


def test_variant_and_multi_return_fall_back(ray_start_4_cpus, monkeypatch):
    from ray_tpu._private.client import CoreClient

    batched = []
    orig = CoreClient.submit_batched

    def spy(self, *a, **k):
        batched.append(1)
        return orig(self, *a, **k)

    monkeypatch.setattr(CoreClient, "submit_batched", spy)

    @ray_tpu.remote(num_returns=2)
    def split(i):
        return i, -i

    a, b = split.remote(5)
    assert ray_tpu.get([a, b], timeout=60) == [5, -5]

    @ray_tpu.remote
    def f(i):
        return i + 1

    assert ray_tpu.get(f.options(name="v").remote(1), timeout=60) == 2
    assert not batched, "num_returns/options() must stay unbatched"

    # the .options() clone is the variant, not the base function: plain
    # calls afterwards still batch
    assert ray_tpu.get([f.remote(i) for i in range(5)], timeout=60) == [
        1, 2, 3, 4, 5,
    ]
    assert batched


@pytest.fixture
def autobatch_off(monkeypatch):
    # env, not RAY_TPU_CONFIG.set(): the hub runs config.reload() at
    # construction, which rebuilds the table from env and would wipe a
    # .set() override before the driver client reads it
    monkeypatch.setenv("RAY_TPU_SUBMIT_AUTOBATCH_WINDOW_US", "0")
    try:
        ctx = ray_tpu.init(num_cpus=2, max_workers=2)
        yield ctx
    finally:
        ray_tpu.shutdown()


def test_window_zero_reverts_to_classic_path(autobatch_off, monkeypatch):
    """submit_autobatch_window_us=0 disables the spliced path entirely:
    every call takes the untouched per-call SUBMIT_TASK code path (the
    frames are byte-identical to the pre-autobatch client's)."""
    from ray_tpu._private import worker
    from ray_tpu._private.client import CoreClient

    client = worker.get_client()
    assert client._ab_window_s == 0.0

    def boom(self, *a, **k):
        raise AssertionError("submit_batched must not run with window=0")

    monkeypatch.setattr(CoreClient, "submit_batched", boom)

    sent = []
    orig = client.submit_task

    def spy(fn_id, *a, **k):
        sent.append(fn_id)
        return orig(fn_id, *a, **k)

    monkeypatch.setattr(client, "submit_task", spy)

    @ray_tpu.remote
    def f(i):
        return i * 3

    assert ray_tpu.get(
        [f.remote(i) for i in range(20)], timeout=60
    ) == [i * 3 for i in range(20)]
    assert len(sent) == 20


# ------------------------------------------------------------------ FIFO


def test_autobatch_fifo_across_drains(ray_start_4_cpus):
    """Admission order must match submission order even when an
    auto-batch is pending: every other outbound message (explicit bulk,
    put, actor call) drains the batch FIRST. Each stamp task claims the
    whole node, so execution is strictly serial and completion
    timestamps reveal admission order."""
    @ray_tpu.remote(num_cpus=4)
    def stamp(_tag):
        return time.monotonic()

    @ray_tpu.remote
    class Tag:
        def tag(self, v):
            return v

    head = stamp.remote("head")                       # pending batch
    mid = stamp.map([(f"m{i}",) for i in range(3)])   # bulk: drains head
    burst = [stamp.remote(f"b{i}") for i in range(6)]  # new pending batch
    x = ray_tpu.put(b"x")                             # put: drains burst
    actor = Tag.remote()
    t = actor.tag.remote("actor")                     # rides post-drain
    tail = stamp.remote("tail")

    times = ray_tpu.get([head, *mid, *burst, tail], timeout=90)
    assert times == sorted(times), "auto-batch broke per-conn FIFO order"
    assert ray_tpu.get(x) == b"x"
    assert ray_tpu.get(t, timeout=60) == "actor"


# ----------------------------------------------------------------- chaos


@pytest.fixture
def chaos_autobatch(monkeypatch):
    """Runtime factory: chaos plan set BEFORE init (the hub reads the
    env at construction); fast retransmit keeps drop tests quick."""
    from ray_tpu._private.client import CoreClient

    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.2)

    def start(plan):
        monkeypatch.setenv("RAY_TPU_CHAOS_PLAN", plan)
        return ray_tpu.init(num_cpus=2, max_workers=2)

    yield start
    ray_tpu.shutdown()


def test_autobatch_survives_hub_drop_and_dup(chaos_autobatch):
    """Hub-scope chaos: half the auto-batched SUBMIT_TASKS frames are
    dropped on arrival (no REPLY -> raw-bytes retransmit) and half are
    delivered twice (per-task dedup on the hub). Every call must still
    produce its result exactly once."""
    chaos_autobatch("seed=13;drop:submit_tasks@0.5;dup:submit_tasks@0.5")

    @ray_tpu.remote
    def f(i):
        return i + 1

    refs = [f.remote(i) for i in range(60)]
    assert ray_tpu.get(refs, timeout=120) == [i + 1 for i in range(60)]


def test_autobatch_survives_client_outbound_chaos(chaos_autobatch):
    """Client-scope chaos: the drain's own outbound_send hook drops or
    duplicates the frame before it ever hits the socket — recovery is
    the same ack/retransmit/dedup triangle."""
    chaos_autobatch(
        "seed=7;drop:client.submit_tasks@0.5;dup:client.submit_tasks@0.5"
    )

    @ray_tpu.remote
    def g(i):
        return i * 2

    refs = [g.remote(i) for i in range(60)]
    assert ray_tpu.get(refs, timeout=120) == [i * 2 for i in range(60)]
