"""ViT model family (models/vit.py): forward shape/finiteness,
training, TP/FSDP-sharded equivalence on the 8-device CPU mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import VIT_TINY, vit


@pytest.fixture(scope="module")
def params():
    return vit.init_params(jax.random.PRNGKey(0), VIT_TINY)


@pytest.fixture(scope="module")
def images():
    return jax.random.uniform(jax.random.PRNGKey(1), (4, 32, 32, 3))


def test_forward_shapes(params, images):
    logits = jax.jit(lambda p, x: vit.forward(p, x, VIT_TINY))(params, images)
    assert logits.shape == (4, VIT_TINY.num_classes)
    assert jnp.isfinite(logits).all()


def test_patchify_roundtrip():
    c = VIT_TINY
    imgs = jnp.arange(2 * 32 * 32 * 3, dtype=jnp.float32).reshape(2, 32, 32, 3)
    patches = vit.patchify(imgs, c)
    assert patches.shape == (2, c.n_patches, c.patch_dim)
    # first patch is the top-left 8x8 block, row-major
    np.testing.assert_array_equal(
        np.asarray(patches[0, 0]).reshape(8, 8, 3), np.asarray(imgs[0, :8, :8])
    )


def test_training_reduces_loss(params, images):
    import optax

    labels = jnp.asarray([0, 1, 2, 3])
    batch = {"image": images, "label": labels}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(
            lambda p_: vit.loss_fn(p_, batch, VIT_TINY)
        )(p)
        updates, s = opt.update(grads, s)
        return optax.apply_updates(p, updates), s, loss

    p = params
    first = None
    for _ in range(15):
        p, opt_state, loss = step(p, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first - 0.5, (first, float(loss))


def test_sharded_forward_matches(params, images):
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("fsdp", "model"))
    specs = vit.param_specs(VIT_TINY)

    def shard_spec(spec):
        return P(*(
            ax if ax in ("fsdp", "model") else None
            for ax in (tuple(spec) if spec else ())
        ))

    sharded = jax.tree.map(
        lambda arr, spec: jax.device_put(arr, NamedSharding(mesh, shard_spec(spec))),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P) or not isinstance(x, dict),
    )
    ref = jax.jit(lambda p, x: vit.forward(p, x, VIT_TINY))(params, images)
    with mesh:
        out = jax.jit(lambda p, x: vit.forward(p, x, VIT_TINY))(sharded, images)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)
