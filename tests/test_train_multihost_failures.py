"""Multi-host hard cases (SURVEY §7.4): a 4-host jax.distributed gang
and host-loss-driven gang restart + elastic resize across agents. Own
module: each test builds its own Cluster, which cannot coexist with
another module's live module-scoped cluster in one driver process."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


# -------------------------------------------------------- hard cases
def test_four_host_gang_rendezvous():
    """A 4-process jax.distributed gang spanning FOUR hosts (each host
    has exactly 1 CPU, so the gang cannot pack smaller) — the pod-scale
    shape of §7.4 on the simulated cluster."""
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.backend import JaxConfig
    from ray_tpu.train.jax_trainer import JaxTrainer

    c = Cluster(head_num_cpus=1)
    for _ in range(3):
        c.add_node(num_cpus=1)
    try:
        def fn(config):
            import os

            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.experimental import multihost_utils

            from ray_tpu.train import session

            gathered = np.asarray(
                multihost_utils.process_allgather(
                    jnp.array([float(jax.process_index())])
                )
            ).reshape(-1)
            session.report({
                "rank_sum": float(gathered.sum()),
                "n_processes": jax.process_count(),
                "node": os.environ.get("RAY_TPU_NODE_ID", "node0"),
            })

        seen_nodes = set()
        trainer = JaxTrainer(
            train_loop_per_worker=fn,
            scaling_config=ScalingConfig(
                num_workers=4, resources_per_worker={"CPU": 1}
            ),
            jax_config=JaxConfig(enable_distributed=True),
        )
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["n_processes"] == 4
        assert result.metrics["rank_sum"] == 6.0  # 0+1+2+3
    finally:
        c.shutdown()


def test_host_loss_triggers_gang_restart_and_elastic_resize(tmp_path):
    """Kill a HOST (agent process) mid-training: the gang worker on it
    dies, the restart at full size is unschedulable on the survivors,
    and elastic resize completes the run at half size from the latest
    checkpoint (§7.4's host-loss + elastic-across-agents case)."""
    import threading
    import time as _time

    from ray_tpu import train
    from ray_tpu.air.config import FailureConfig, ScalingConfig
    from ray_tpu.train import Checkpoint, RunConfig
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

    c = Cluster(head_num_cpus=1)
    node_b = c.add_node(num_cpus=1)
    marker = tmp_path / "rank1_started"
    try:
        def loop(config):
            import os

            from ray_tpu.train import session

            ctx = session.get_context()
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                start = ckpt.to_state()["step"] + 1
            for i in range(start, 4):
                if i == 2 and ctx.get_world_size() == 2:
                    # full-size attempt parks the WHOLE gang at step 2:
                    # the off-head rank signals the driver and both
                    # ranks wait for the host kill (gang is
                    # all-or-nothing — rank 0 must not finish early)
                    import time

                    if os.environ.get("RAY_TPU_NODE_ID", "node0") != "node0":
                        open(config["marker"], "w").close()
                    time.sleep(120)
                session.report(
                    {"step": i, "world": ctx.get_world_size()},
                    checkpoint=Checkpoint.from_state({"step": i}),
                )

        trainer = DataParallelTrainer(
            loop,
            train_loop_config={"marker": str(marker)},
            scaling_config=ScalingConfig(
                num_workers=2,
                resources_per_worker={"CPU": 1},
                min_workers=1,
                placement_timeout_s=3.0,
            ),
            run_config=RunConfig(
                name="hostloss", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=3),
            ),
        )

        def killer():
            deadline = _time.monotonic() + 60
            while _time.monotonic() < deadline:
                if marker.exists():
                    c.remove_node(node_b)
                    return
                _time.sleep(0.2)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        result = trainer.fit()
        kt.join(timeout=60)
        assert result.error is None, result.error
        # survived the host loss; finished all steps at reduced size
        assert result.metrics["step"] == 3
        assert result.metrics["world"] == 1
        assert marker.exists()  # the doomed rank really ran on node B
    finally:
        c.shutdown()
