"""Core task API tests.

Modeled on the reference's python/ray/tests/test_basic.py coverage:
remote functions, args/kwargs, ObjectRef passing, multiple returns,
errors, nested tasks, wait, timeouts, large objects.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(a, b):
        return a + b

    assert ray_tpu.get(f.remote(1, 2)) == 3


def test_kwargs_and_defaults(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=10, *, c=100):
        return a + b + c

    assert ray_tpu.get(f.remote(1)) == 111
    assert ray_tpu.get(f.remote(1, b=2, c=3)) == 6


def test_object_ref_arg_resolution(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x * 2

    r1 = f.remote(5)
    r2 = f.remote(r1)  # top-level ref resolved to its value
    assert ray_tpu.get(r2) == 20


def test_put_get_roundtrip(ray_start_regular):
    obj = {"a": [1, 2, 3], "b": "hello"}
    assert ray_tpu.get(ray_tpu.put(obj)) == obj


def test_put_on_ref_raises(ray_start_regular):
    with pytest.raises(TypeError):
        ray_tpu.put(ray_tpu.put(1))


def test_large_object_zero_copy(ray_start_regular):
    arr = np.arange(500_000, dtype=np.float64)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(ref)) == float(arr.sum())
    # the driver-side get should give back an equal array
    got = ray_tpu.get(ref)
    np.testing.assert_array_equal(got, arr)


def test_large_arg_auto_spill(ray_start_regular):
    arr = np.ones(200_000, dtype=np.float64)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(arr)) == 200_000.0


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("boom")

    with pytest.raises(TaskError, match="boom"):
        ray_tpu.get(boom.remote())


def test_error_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def boom():
        raise ValueError("boom")

    @ray_tpu.remote
    def dependent(x):
        return x

    # the dependent task's get should surface the upstream error
    with pytest.raises(TaskError):
        ray_tpu.get(dependent.remote(boom.remote()))


def test_nested_task_submission(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 100

    assert ray_tpu.get(outer.remote(1)) == 102


def test_wait_basic(ray_start_regular):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 2

    refs = [fast.remote(), slow.remote()]
    ready, not_ready = ray_tpu.wait(refs, num_returns=1, timeout=4)
    assert len(ready) == 1 and len(not_ready) == 1
    assert ray_tpu.get(ready[0]) == 1


def test_wait_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    ready, not_ready = ray_tpu.wait([slow.remote()], num_returns=1, timeout=0.2)
    assert ready == [] and len(not_ready) == 1


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(10)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_options_override(ray_start_regular):
    @ray_tpu.remote(num_returns=1)
    def f():
        return 1, 2

    a, b = f.options(num_returns=2).remote()
    assert ray_tpu.get([a, b]) == [1, 2]


def test_calling_remote_directly_raises(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_many_tasks(ray_start_regular):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(200)]
    assert ray_tpu.get(refs) == [i * i for i in range(200)]


def test_cluster_resources(ray_start_regular):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 2.0
    avail = ray_tpu.available_resources()
    assert avail["CPU"] <= res["CPU"]


def test_nodes(ray_start_regular):
    ns = ray_tpu.nodes()
    assert len(ns) == 1 and ns[0]["alive"]


def test_get_runtime_context(ray_start_regular):
    """ray_tpu.get_runtime_context(): node/worker/task/actor identity
    (reference: ray.runtime_context.RuntimeContext)."""
    ctx = ray_tpu.get_runtime_context()
    assert ctx.get_node_id()
    assert ctx.get_worker_id() == "driver"
    assert ctx.get_task_id() is None
    assert ctx.get_actor_id() is None

    @ray_tpu.remote
    def who():
        c = ray_tpu.get_runtime_context()
        return c.get_task_id(), c.get_actor_id(), c.get_worker_id()

    task_id, actor_id, worker_id = ray_tpu.get(who.remote())
    assert task_id and actor_id is None
    assert worker_id != "driver"

    @ray_tpu.remote
    class A:
        def who(self):
            c = ray_tpu.get_runtime_context()
            return c.get_task_id(), c.get_actor_id()

    a = A.remote()
    t1, aid = ray_tpu.get(a.who.remote())
    t2, aid2 = ray_tpu.get(a.who.remote())
    assert aid and aid == aid2
    assert t1 and t2 and t1 != t2
