"""Podracer subsystem (rllib/podracer): Anakin & Sebulba end-to-end on
CPU, same-seed bitwise determinism, the direct-object-plane trajectory
hand-off, trace-stage attribution, and seeded learner-kill chaos
resume.

Everything here runs under JAX_PLATFORMS=cpu with the conftest's 8
virtual devices — the MULTICHIP topology is exercised in shape only
(mesh/shard_map/collective group), never in silicon.
"""

import pickle
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.podracer import PodracerConfig


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def _hub():
    return ray_tpu._private.worker._hub


def _anakin_config(seed=3):
    return (
        PodracerConfig()
        .environment("CartPole-v1")
        .podracer(mode="anakin", num_envs=32, anakin_supersteps_per_call=2)
        .env_runners(rollout_fragment_length=16)
        .debugging(seed=seed)
    )


def _sebulba_config(namespace, **overrides):
    cfg = (
        PodracerConfig()
        .environment("CartPole-v1")
        .podracer(mode="sebulba", namespace=namespace)
        .debugging(seed=7)
    )
    return cfg.training(**overrides) if overrides else cfg


# ------------------------------------------------------------- config surface


def test_config_validation():
    with pytest.raises(ValueError, match="mode"):
        PodracerConfig().podracer(mode="impala").validate()
    with pytest.raises(ValueError, match="loss"):
        PodracerConfig().training(loss="sarsa").validate()
    with pytest.raises(ValueError, match="no pure-JAX env"):
        PodracerConfig().environment("Walker2d-v4").validate()
    # sebulba env total must shard evenly over the learner group
    with pytest.raises(ValueError, match="learner_shards"):
        (
            PodracerConfig()
            .podracer(mode="sebulba", learner_shards=3)
            .env_runners(num_actors=1, envs_per_actor=16)
            .validate()
        )


def test_podracer_stages_registered():
    """The four podracer stages sit in STAGE_PRECEDENCE above worker
    execute, so analyze_trace charges in-task time to the RL phase."""
    from ray_tpu.util.tracing import STAGE_PRECEDENCE

    execute = STAGE_PRECEDENCE["execute"]
    for stage in (
        "podracer.env_step",
        "podracer.learner_update",
        "podracer.traj_handoff",
        "podracer.param_sync",
    ):
        assert STAGE_PRECEDENCE[stage] > execute


# --------------------------------------------------------------------- anakin


def test_anakin_trains_and_is_bitwise_deterministic(ray_start_4_cpus):
    """Two same-seed Anakin runs (compiled-DAG resident loop) reproduce
    the whole metrics stream bitwise on CPU — the Podracer determinism
    contract: every superstep key is fold_in(seed_key, k)."""

    def run():
        driver = _anakin_config(seed=3).build()
        try:
            return driver.train(num_ticks=4)
        finally:
            driver.stop()

    r1 = run()
    assert r1["mode"] == "anakin"
    assert r1["ticks"] == 4
    assert r1["updates"] == 8  # 4 ticks x anakin_supersteps_per_call=2
    assert r1["env_steps_total"] == 8 * 16 * 32  # updates x T x num_envs
    assert r1["steps_per_sec"] > 0
    assert r1["metrics_rows"].shape == (4, 10)
    assert np.isfinite(r1["vf_loss"]) and np.isfinite(r1["entropy"])
    # CartPole rewards 1/step: any completed episode has a positive mean
    assert r1["num_episodes"] > 0 and r1["episode_return_mean"] > 0

    r2 = run()
    assert np.array_equal(r1["metrics_rows"], r2["metrics_rows"])
    assert r1["reward_trajectory"] == r2["reward_trajectory"]


# -------------------------------------------------------------------- sebulba


def test_sebulba_handoff_rides_object_plane(ray_start_4_cpus):
    """A Sebulba rollout fragment (>=100KiB) must cross actor->learner
    as a shm-backed object (direct object plane), never as hub-relayed
    payload bytes — and the full round loop trains end to end."""
    cfg = _sebulba_config(
        "handoff",
        num_actors=2,
        envs_per_actor=32,
        rollout_fragment_length=128,
        learner_shards=2,
        num_sgd_steps=1,
        max_inflight_rounds=1,
    )
    driver = cfg.build()
    try:
        # one fragment by hand, refs held, so the directory entry is
        # still live to inspect
        traj_ref, carry_ref = driver._sample.remote(
            driver._cfg_blob, 0, 0, None
        )
        traj = ray_tpu.get(traj_ref, timeout=300)
        payload = sum(
            a.nbytes for a in traj.values() if isinstance(a, np.ndarray)
        )
        assert payload >= 100 * 1024  # the test premise: big enough to spill

        rows = {r["object_id"]: r for r in _client().list_state("objects")}
        trow = rows[traj_ref._id.hex()]
        assert trow["kind"] == "shm"  # VAL_SHM: segment name, not bytes
        assert trow["size"] >= 100 * 1024
        # the carry continuation is small: must NOT occupy a segment
        crow = rows.get(carry_ref._id.hex())
        assert crow is None or crow["kind"] != "shm"
        # zero hub relay: no PUT_CHUNK frames carried rollout payloads
        relay = _hub().metrics.get(
            ("ray_tpu_hub_messages_total", (("type", "put_chunk"),))
        )
        assert relay is None or relay["value"] == 0

        res = driver.train(num_rounds=3)
    finally:
        driver.stop()

    assert res["mode"] == "sebulba"
    assert res["learner_step"] == 3
    assert res["param_version"] == 3  # param_sync_interval=1: every step
    assert sorted(res["learner_steps"]) == [1, 2, 3]
    assert res["env_steps"] == 3 * 2 * 32 * 128
    assert res["steps_per_sec"] > 0
    # bounded staleness: behaviour versions lag the learner, never lead
    assert max(res["learner_metrics"]["behavior_versions"]) <= 3


# -------------------------------------------------------------------- tracing


@pytest.fixture
def traced_podracer(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    ctx = ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


def _find_trace_with_span(span_name, deadline_s=20.0):
    client = _client()
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for row in client.list_state("traces"):
            spans = client.list_state("traces", trace_id=row["trace_id"])
            if any(s.get("name") == span_name for s in spans):
                return spans
        time.sleep(0.1)
    raise AssertionError(f"no trace contains a {span_name!r} span")


def test_trace_stages_answer_actor_or_learner_bound(traced_podracer):
    """Traced Sebulba round: analyze_trace on the learner task's trace
    reports podracer.traj_handoff + podracer.learner_update stages, the
    actor task's trace reports podracer.env_step + podracer.param_sync —
    the stage split that answers 'actor-bound or learner-bound'."""
    from ray_tpu.util.tracing import analyze_trace

    cfg = _sebulba_config(
        "traced",
        num_actors=2,
        envs_per_actor=4,
        rollout_fragment_length=8,
        learner_shards=1,
        max_inflight_rounds=1,
    )
    driver = cfg.build()
    try:
        driver.train(num_rounds=2)
    finally:
        driver.stop()

    def stage_s(analysis, stage):
        return analysis["stages"].get(stage, {}).get("dur_s", 0.0)

    learner_spans = _find_trace_with_span("podracer.learner_update")
    analysis = analyze_trace(learner_spans)
    assert stage_s(analysis, "podracer.learner_update") > 0
    assert stage_s(analysis, "podracer.traj_handoff") > 0
    assert analysis["dominant_stage"] is not None

    actor_spans = _find_trace_with_span("podracer.env_step")
    analysis = analyze_trace(actor_spans)
    assert stage_s(analysis, "podracer.env_step") > 0
    assert stage_s(analysis, "podracer.param_sync") > 0


def test_anakin_traced_mode_splits_the_fused_loop(traced_podracer):
    """With tracing live the resident worker runs the acting scan and
    the update as two spanned programs (the fused superstep is opaque),
    so the on-chip loop still shows up stage-attributed."""
    cfg = (
        PodracerConfig()
        .environment("CartPole-v1")
        .podracer(mode="anakin", num_envs=8, use_compiled_dag=False)
        .env_runners(rollout_fragment_length=8)
        .debugging(seed=1)
    )
    driver = cfg.build()
    try:
        res = driver.train(num_ticks=2)
    finally:
        driver.stop()
    assert res["updates"] == 2

    spans = _find_trace_with_span("podracer.env_step")
    by_name = {s.get("name") for s in spans}
    assert "podracer.learner_update" in by_name
    modes = {
        (s.get("attrs") or {}).get("mode")
        for s in spans
        if s.get("name") == "podracer.env_step"
    }
    assert "anakin" in modes


# ------------------------------------------------------------ chaos: learner


def _chaos_rows():
    return _client().list_state("chaos")


def test_learner_kill_resumes_from_published_state(monkeypatch):
    """A chaos worker_kill lands mid learner_update (the only plain
    task in flight); lineage retry replays it against the same state
    ref + trajectory args, so the step counter resumes monotonically
    and the same param version is (re)published on the KV channel."""
    monkeypatch.setenv(
        "RAY_TPU_CHAOS_PLAN", "seed=5;worker_kill:1@2s"
    )
    ray_tpu.init(num_cpus=4, max_workers=4)
    try:
        cfg = _sebulba_config(
            "killres",
            num_actors=2,
            envs_per_actor=32,
            rollout_fragment_length=16,
            learner_shards=2,
            num_sgd_steps=1500,  # keeps the learner busy past the kill
        )
        driver = cfg.build()
        try:
            # synthetic trajectories (no actor tasks): the learner is
            # the only worker the cluster ever spawns, so the seeded
            # busy-plain-first victim choice is fully deterministic
            rng = np.random.default_rng(0)
            T, N = cfg.rollout_fragment_length, cfg.envs_per_actor

            def fake_traj():
                return {
                    "obs": rng.standard_normal((T, N, 4)).astype(np.float32),
                    "actions": rng.integers(0, 2, (T, N)).astype(np.int32),
                    "rewards": np.ones((T, N), np.float32),
                    "dones": (rng.random((T, N)) < 0.02).astype(np.float32),
                    "logp_mu": np.full((T, N), -0.693, np.float32),
                    "final_obs": rng.standard_normal((N, 4)).astype(
                        np.float32
                    ),
                    "behavior_version": 0,
                }

            trajs = [fake_traj(), fake_traj()]
            state_ref, metrics_ref = driver._learn.remote(
                driver._cfg_blob, driver._state_ref, *trajs
            )
            metrics = ray_tpu.get(metrics_ref, timeout=300)
            assert metrics["step"] == 1
            assert metrics["version"] == 1

            # the kill fired, and exactly per plan
            rows = _chaos_rows()
            assert rows[0]["counts"].get("worker_kill") == 1
            assert [
                r["kind"] for r in rows[1:] if r.get("kind", "").startswith("chaos_")
            ] == ["chaos_worker_kill"]

            # the channel carries the resumed version's params
            blob = _client().kv_get(b"podracer/killres/params")
            version, _params = pickle.loads(blob)
            assert version == 1

            # chain a second step on the survived state: monotone resume
            state_ref, metrics_ref = driver._learn.remote(
                driver._cfg_blob, state_ref, *trajs
            )
            assert ray_tpu.get(metrics_ref, timeout=300)["step"] == 2
        finally:
            driver.stop()
    finally:
        ray_tpu.shutdown()


SOAK_PLAN = "seed=11;worker_kill:1@6s"


def _soak_once():
    """One seeded Sebulba training soak under a mid-training
    worker_kill; returns (train result, chaos event kinds, counts)."""
    ray_tpu.init(num_cpus=4, max_workers=4)
    try:
        cfg = _sebulba_config(
            "soak",
            num_actors=2,
            envs_per_actor=8,
            rollout_fragment_length=16,
            learner_shards=1,
            num_sgd_steps=600,  # learner-bound: the busy victim tier
            max_inflight_rounds=1,
        )
        driver = cfg.build()
        try:
            res = driver.train(num_rounds=5)
        finally:
            driver.stop()
        rows = _chaos_rows()
        kinds = [
            r["kind"] for r in rows[1:] if r.get("kind", "").startswith("chaos_")
        ]
        return res, kinds, dict(rows[0]["counts"])
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow  # two full cluster cycles with a 6s-delayed kill (~20s)
def test_learner_kill_soak_twice_same_seed(monkeypatch):
    """The acceptance soak: same seeded chaos plan twice -> identical
    fault sequence, and both runs finish all rounds with a
    monotonically advancing learner step counter (no wedged actors).

    The fast single-kill variant above stays in tier-1; this
    reproducibility soak runs via a plain `pytest tests/test_podracer.py`."""
    monkeypatch.setenv("RAY_TPU_CHAOS_PLAN", SOAK_PLAN)
    res1, kinds1, counts1 = _soak_once()
    res2, kinds2, counts2 = _soak_once()

    # identical fault sequence across the two runs
    assert kinds1 == kinds2
    assert counts1 == counts2
    assert counts1.get("worker_kill") == 1

    for res in (res1, res2):
        # every round's learner step landed, strictly increasing: the
        # kill cost a retry, never a lost or repeated step
        assert res["learner_steps"] == [1, 2, 3, 4, 5]
        assert res["learner_step"] == 5
        assert res["env_steps"] == 5 * 2 * 8 * 16
        # actors kept sampling throughout (episodes kept completing)
        assert res["num_episodes"] > 0
