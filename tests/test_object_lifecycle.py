"""Object-store lifecycle: LRU eviction under a configurable cap with
disk spill/restore (reference: plasma eviction_policy.h LRU +
_private/external_storage.py filesystem spilling), plus the hub
get/wait waiter-leak regression (r1 Weak #10)."""

import os

import numpy as np
import pytest

import ray_tpu


OBJ_MB = 4
CAP_BYTES = 24 * 1024 * 1024  # room for ~5 segments


@pytest.fixture
def capped_runtime():
    ctx = ray_tpu.init(
        num_cpus=2, max_workers=2, object_store_memory=CAP_BYTES
    )
    yield ctx
    ray_tpu.shutdown()


def _session_objects_bytes():
    sdir = ray_tpu._private.worker._session_dir
    odir = os.path.join(sdir, "objects")
    return sum(
        os.path.getsize(os.path.join(odir, f)) for f in os.listdir(odir)
    )


def test_create_2x_cap_completes_and_stays_bounded(capped_runtime):
    """2x the cap of live objects: puts keep succeeding, shm stays at
    ~cap (cold segments spill to disk), every value remains readable."""
    n = 2 * CAP_BYTES // (OBJ_MB * 1024 * 1024)
    refs = []
    for i in range(n):
        arr = np.full((OBJ_MB * 1024 * 1024 // 8,), float(i))
        refs.append(ray_tpu.put(arr))
    hub = ray_tpu._private.worker._hub
    assert hub.nodes["node0"].store_used <= CAP_BYTES
    assert _session_objects_bytes() <= CAP_BYTES + OBJ_MB * 1024 * 1024
    spilled = [
        e for e in hub.objects.values() if e.spilled
    ]
    assert spilled, "expected cold segments to spill"
    # every object still readable — including spilled ones (restore path).
    # Read via a fresh worker process (its local store has none of the
    # driver's cached mmaps).

    @ray_tpu.remote
    def first(x):
        return float(x[0])

    for i, ref in enumerate(refs):
        assert ray_tpu.get(first.remote(ref)) == float(i)


def test_spilled_object_direct_get_restores(capped_runtime):
    big = np.arange(CAP_BYTES // 2 // 8, dtype=np.float64)
    ref0 = ray_tpu.put(big)
    hub = ray_tpu._private.worker._hub
    oid0 = ref0._id.binary()
    # push it out with newer objects
    keep = [ray_tpu.put(np.zeros(CAP_BYTES // 2 // 8)) for _ in range(3)]
    assert hub.objects[oid0].spilled
    # driver get (same node): hub restores the segment under accounting

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(ref0)) == float(big.sum())
    assert not hub.objects[oid0].spilled
    assert hub.nodes["node0"].store_used <= CAP_BYTES


def test_free_cleans_spill_files(capped_runtime):
    refs = [
        ray_tpu.put(np.zeros(OBJ_MB * 1024 * 1024 // 8)) for _ in range(10)
    ]
    hub = ray_tpu._private.worker._hub
    assert any(e.spilled for e in hub.objects.values())
    ray_tpu.free(refs)
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if not os.path.isdir(hub.spill_dir) or not os.listdir(hub.spill_dir):
            break
        time.sleep(0.05)
    assert not os.path.isdir(hub.spill_dir) or not os.listdir(hub.spill_dir)
    assert hub.nodes["node0"].store_used == 0


def test_get_timeout_unregisters_waiter(capped_runtime):
    from ray_tpu.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID

    hub = ray_tpu._private.worker._hub
    ghost = ObjectRef(ObjectID.generate())
    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        ray_tpu.get(ghost, timeout=0.2)
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if not hub.obj_get_waiters.get(ghost._id.binary()):
            break
        time.sleep(0.05)
    assert not hub.obj_get_waiters.get(ghost._id.binary())


def test_wait_timeout_unregisters_waiter(capped_runtime):
    from ray_tpu.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID

    hub = ray_tpu._private.worker._hub
    ghost = ObjectRef(ObjectID.generate())
    ready, not_ready = ray_tpu.wait([ghost], num_returns=1, timeout=0.2)
    assert not ready and len(not_ready) == 1
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if not hub.obj_wait_waiters.get(ghost._id.binary()):
            break
        time.sleep(0.05)
    assert not hub.obj_wait_waiters.get(ghost._id.binary())


# ----------------------------------------------------- segment-pool cap


def test_concurrent_free_respects_pool_cap(tmp_path, monkeypatch):
    """Regression: free() used to check pool room under one lock
    acquisition and insert under another, so concurrent frees could all
    pass the byte-cap test and blow past _POOL_MAX_BYTES. The fixed
    path re-checks and inserts under a single acquisition."""
    import threading

    from ray_tpu._private import object_store as os_mod
    from ray_tpu._private.object_store import ShmObjectStore

    seg_payload = np.zeros(64 * 1024, np.uint8)
    store = ShmObjectStore(str(tmp_path))
    # one segment comfortably over half the cap: ANY two pooled
    # segments exceed it, so a double-insert is always a cap breach
    size = store.put("probe", seg_payload)
    monkeypatch.setattr(os_mod, "_POOL_MAX_BYTES", int(size * 1.5))
    store.free("probe")

    for round_i in range(10):
        names = [f"obj{round_i}_{j}" for j in range(4)]
        for n in names:
            store.put(n, seg_payload)
        barrier = threading.Barrier(len(names))

        def free_one(name):
            barrier.wait()
            store.free(name)

        threads = [
            threading.Thread(target=free_one, args=(n,)) for n in names
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with store._lock:
            assert store._pool_bytes <= int(size * 1.5), (
                round_i, store._pool_bytes
            )
            assert len(store._pool) <= os_mod._POOL_MAX_SEGMENTS
            assert store._pool_bytes == sum(c for c, _ in store._pool)


def test_free_unpooled_segment_is_unlinked(tmp_path, monkeypatch):
    """When the pool has no room, the renamed segment file must be
    unlinked, not leaked under its anonymous .pool.* name."""
    from ray_tpu._private import object_store as os_mod
    from ray_tpu._private.object_store import ShmObjectStore

    store = ShmObjectStore(str(tmp_path))
    monkeypatch.setattr(os_mod, "_POOL_MAX_SEGMENTS", 1)
    a = np.zeros(32 * 1024, np.uint8)
    store.put("a", a)
    store.put("b", a)
    store.free("a")  # fills the single pool slot
    store.free("b")  # no room: must unlink, not pool
    with store._lock:
        assert len(store._pool) == 1
    leftovers = [
        f for f in os.listdir(store.dir) if not f.startswith(".pool.")
    ]
    assert leftovers == []
    assert len([f for f in os.listdir(store.dir)]) == 1
