"""Object-store lifecycle: LRU eviction under a configurable cap with
disk spill/restore (reference: plasma eviction_policy.h LRU +
_private/external_storage.py filesystem spilling), plus the hub
get/wait waiter-leak regression (r1 Weak #10)."""

import os

import numpy as np
import pytest

import ray_tpu


OBJ_MB = 4
CAP_BYTES = 24 * 1024 * 1024  # room for ~5 segments


@pytest.fixture
def capped_runtime():
    ctx = ray_tpu.init(
        num_cpus=2, max_workers=2, object_store_memory=CAP_BYTES
    )
    yield ctx
    ray_tpu.shutdown()


def _session_objects_bytes():
    sdir = ray_tpu._private.worker._session_dir
    odir = os.path.join(sdir, "objects")
    return sum(
        os.path.getsize(os.path.join(odir, f)) for f in os.listdir(odir)
    )


def test_create_2x_cap_completes_and_stays_bounded(capped_runtime):
    """2x the cap of live objects: puts keep succeeding, shm stays at
    ~cap (cold segments spill to disk), every value remains readable."""
    n = 2 * CAP_BYTES // (OBJ_MB * 1024 * 1024)
    refs = []
    for i in range(n):
        arr = np.full((OBJ_MB * 1024 * 1024 // 8,), float(i))
        refs.append(ray_tpu.put(arr))
    hub = ray_tpu._private.worker._hub
    assert hub.nodes["node0"].store_used <= CAP_BYTES
    assert _session_objects_bytes() <= CAP_BYTES + OBJ_MB * 1024 * 1024
    spilled = [
        e for e in hub.objects.values() if e.spilled
    ]
    assert spilled, "expected cold segments to spill"
    # every object still readable — including spilled ones (restore path).
    # Read via a fresh worker process (its local store has none of the
    # driver's cached mmaps).

    @ray_tpu.remote
    def first(x):
        return float(x[0])

    for i, ref in enumerate(refs):
        assert ray_tpu.get(first.remote(ref)) == float(i)


def test_spilled_object_direct_get_restores(capped_runtime):
    big = np.arange(CAP_BYTES // 2 // 8, dtype=np.float64)
    ref0 = ray_tpu.put(big)
    hub = ray_tpu._private.worker._hub
    oid0 = ref0._id.binary()
    # push it out with newer objects
    keep = [ray_tpu.put(np.zeros(CAP_BYTES // 2 // 8)) for _ in range(3)]
    assert hub.objects[oid0].spilled
    # driver get (same node): hub restores the segment under accounting

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(ref0)) == float(big.sum())
    assert not hub.objects[oid0].spilled
    assert hub.nodes["node0"].store_used <= CAP_BYTES


def test_free_cleans_spill_files(capped_runtime):
    refs = [
        ray_tpu.put(np.zeros(OBJ_MB * 1024 * 1024 // 8)) for _ in range(10)
    ]
    hub = ray_tpu._private.worker._hub
    assert any(e.spilled for e in hub.objects.values())
    ray_tpu.free(refs)
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if not os.path.isdir(hub.spill_dir) or not os.listdir(hub.spill_dir):
            break
        time.sleep(0.05)
    assert not os.path.isdir(hub.spill_dir) or not os.listdir(hub.spill_dir)
    assert hub.nodes["node0"].store_used == 0


def test_get_timeout_unregisters_waiter(capped_runtime):
    from ray_tpu.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID

    hub = ray_tpu._private.worker._hub
    ghost = ObjectRef(ObjectID.generate())
    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        ray_tpu.get(ghost, timeout=0.2)
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if not hub.obj_get_waiters.get(ghost._id.binary()):
            break
        time.sleep(0.05)
    assert not hub.obj_get_waiters.get(ghost._id.binary())


def test_wait_timeout_unregisters_waiter(capped_runtime):
    from ray_tpu.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID

    hub = ray_tpu._private.worker._hub
    ghost = ObjectRef(ObjectID.generate())
    ready, not_ready = ray_tpu.wait([ghost], num_returns=1, timeout=0.2)
    assert not ready and len(not_ready) == 1
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        if not hub.obj_wait_waiters.get(ghost._id.binary()):
            break
        time.sleep(0.05)
    assert not hub.obj_wait_waiters.get(ghost._id.binary())
