"""Multi-agent RL tests (reference pattern:
rllib/env/tests/test_multi_agent_env_runner.py + tuned_examples
multi-agent CartPole convergence)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    MultiAgentEnv,
    MultiAgentEpisode,
    MultiAgentEnvRunner,
    PPOConfig,
    make_multi_agent,
)


# ------------------------------------------------------------- env API
def test_make_multi_agent_env_api():
    env = make_multi_agent("CartPole-v1")({"num_agents": 3})
    assert env.possible_agents == ["agent_0", "agent_1", "agent_2"]
    obs, infos = env.reset(seed=7)
    assert set(obs) == set(env.possible_agents)
    acts = {a: env.get_action_space(a).sample() for a in env.agents}
    obs, rew, term, trunc, infos = env.step(acts)
    assert set(rew) == set(env.possible_agents)
    assert term["__all__"] is False
    # run until one sub-env terminates: that agent must drop out of
    # `agents`; an already-done agent never reappears in obs (the step
    # it dies it still returns its final obs, like the reference)
    for _ in range(500):
        done_before = [a for a in env.possible_agents if a not in env.agents]
        acts = {a: env.get_action_space(a).sample() for a in env.agents}
        obs, rew, term, trunc, infos = env.step(acts)
        for a in done_before:
            assert a not in obs
        if term["__all__"]:
            break
    assert term["__all__"] is True


# ------------------------------------------------- episode bookkeeping
def test_multi_agent_episode_turn_based_rewards():
    """A reward arriving while an agent is not acting accrues to its
    LAST action (reference: MultiAgentEpisode agent-step mapping)."""
    ep = MultiAgentEpisode(lambda aid: "default_policy")
    ep.add_env_reset({"a": [0.0], "b": [1.0]}, {})
    ep.add_action("a", 1, -0.5, 0.1)
    ep.add_action("b", 0, -0.5, 0.2)
    # only b acts this turn, but a receives a delayed reward
    ep.add_env_step({"b": [1.1]}, {"a": 5.0, "b": 1.0},
                    {"__all__": False}, {"__all__": False}, {})
    ep.add_action("b", 1, -0.6, 0.3)
    ep.add_env_step({"a": [0.2], "b": [1.2]}, {"a": 2.0, "b": 1.0},
                    {"__all__": True, "a": True, "b": True},
                    {"__all__": False}, {})
    seqs = ep.extract_sequences()["default_policy"]
    by_len = sorted(seqs, key=lambda s: len(s["actions"]))
    a_seq = by_len[0]
    assert a_seq["rewards"].tolist() == [7.0]  # 5.0 + 2.0 on one action
    assert ep.total_return() == pytest.approx(9.0)
    assert a_seq["terminated"] and by_len[1]["terminated"]


def test_episode_cut_carries_live_agents():
    ep = MultiAgentEpisode(lambda aid: "m")
    ep.add_env_reset({"a": [0.0], "b": [1.0]}, {})
    ep.add_action("a", 0, 0.0, 0.0)
    ep.add_action("b", 0, 0.0, 0.0)
    ep.add_env_step({"a": [0.1], "b": [1.1]}, {"a": 1.0, "b": 1.0},
                    {"__all__": False, "a": True}, {"__all__": False}, {})
    nxt = ep.cut()
    # a terminated -> dropped; b carries its last obs and running return
    assert list(nxt.tracks) == ["b"]
    assert nxt.tracks["b"].ep_return == pytest.approx(1.0)
    assert nxt.tracks["b"].obs[0].tolist() == [np.float32(1.1)]


# ------------------------------------------------------------- runner
def test_runner_groups_by_module_and_batches():
    runner = MultiAgentEnvRunner(
        make_multi_agent("CartPole-v1"),
        policy_mapping_fn=lambda aid, ep: f"p{int(aid[-1]) % 2}",
        env_config={"num_agents": 4},
        num_envs=2,
        seed=3,
        rollout_fragment_length=16,
    )
    specs = runner.module_specs()
    assert set(specs) == {"p0", "p1"} and specs["p0"] == (4, 2)
    import jax

    from ray_tpu.rllib.core import MLPSpec, init_mlp_module

    params = {
        m: init_mlp_module(jax.random.PRNGKey(i), MLPSpec(4, 2))
        for i, m in enumerate(specs)
    }
    out = runner.sample(params, rng_seed=0)
    assert out["env_steps"] == 2 * 16
    for m in ("p0", "p1"):
        seqs = out["sequences"][m]
        assert seqs and all(len(s["actions"]) >= 1 for s in seqs)
        # fragment-cut sequences bootstrap from a final obs
        assert any(s["final_obs"] is not None for s in seqs)


# ------------------------------------------------------- convergence
@pytest.fixture
def ma_algo(ray_start_4_cpus):
    config = (
        PPOConfig()
        .environment(make_multi_agent("CartPole-v1"),
                     env_config={"num_agents": 2})
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=3e-3, minibatch_size=64, num_epochs=4,
                  entropy_coeff=0.01)
        .multi_agent(
            policies={"shared"},
            policy_mapping_fn=lambda aid, ep: "shared",
        )
        .debugging(seed=42)
    )
    a = config.build_algo()
    yield a
    a.stop()


def test_multi_agent_ppo_shared_policy_learns(ma_algo, tmp_path):
    result = ma_algo.train()
    assert result["training_iteration"] == 1
    assert "shared" in result["learner"]
    assert np.isfinite(result["learner"]["shared"]["policy_loss"])
    first = last = (
        result["episode_return_mean"] if result["num_episodes"] else None
    )
    for _ in range(11):
        r = ma_algo.train()
        if first is None and r["num_episodes"] > 0:
            first = r["episode_return_mean"]
        if r["num_episodes"] > 0:
            last = r["episode_return_mean"]
    # 2-agent CartPole: total return is the SUM over both agents
    # (random ~40); PPO must be well up after ~12 iterations
    assert first is not None and last is not None
    assert last > first + 30, (first, last)

    path = ma_algo.save(str(tmp_path / "ck"))
    it = ma_algo.iteration
    ma_algo.train()
    ma_algo.restore(path)
    assert ma_algo.iteration == it

    import gymnasium as gym

    obs, _ = gym.make("CartPole-v1").reset(seed=0)
    assert ma_algo.compute_single_action(obs, "shared") in (0, 1)


def test_multi_agent_independent_policies(ray_start_4_cpus):
    """Two modules trained side by side: params must diverge from each
    other and both must update every iteration."""
    config = (
        PPOConfig()
        .environment(make_multi_agent("CartPole-v1"),
                     env_config={"num_agents": 2})
        .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                     rollout_fragment_length=32)
        .training(lr=3e-3, minibatch_size=32, num_epochs=2)
        .multi_agent(
            policies={"p_even", "p_odd"},
            policy_mapping_fn=lambda aid, ep: (
                "p_even" if int(aid[-1]) % 2 == 0 else "p_odd"
            ),
        )
        .debugging(seed=7)
    )
    algo = config.build_algo()
    try:
        before = {
            m: np.asarray(p["pi"]["w"]).copy()
            for m, p in algo.params.items()
        }
        assert set(before) == {"p_even", "p_odd"}
        r = algo.train()
        assert set(r["learner"]) == {"p_even", "p_odd"}
        for m in ("p_even", "p_odd"):
            assert not np.allclose(
                before[m], np.asarray(algo.params[m]["pi"]["w"])
            ), f"module {m} did not update"
    finally:
        algo.stop()


def test_policy_mapping_validation(ray_start_4_cpus):
    config = (
        PPOConfig()
        .environment(make_multi_agent("CartPole-v1"),
                     env_config={"num_agents": 2})
        .env_runners(num_env_runners=1)
        .multi_agent(
            policies={"exists", "orphan"},
            policy_mapping_fn=lambda aid, ep: "exists",
        )
    )
    with pytest.raises(ValueError, match="orphan"):
        config.build_algo()


# ------------------------------------------------------------ connectors
def test_connector_units():
    """ConnectorV2 pieces (reference: rllib/connectors/): flatten,
    running-mean-std normalize, per-agent frame stacking with peek."""
    from ray_tpu.rllib import (
        ConnectorPipelineV2,
        FlattenObservations,
        FrameStackObservations,
        NormalizeObservations,
    )

    flat = FlattenObservations()
    out = flat({"obs": np.ones((3, 2, 2))})
    assert out["obs"].shape == (3, 4)

    norm = NormalizeObservations()
    rng = np.random.default_rng(0)
    data = rng.normal(5.0, 3.0, size=(500, 4)).astype(np.float32)
    norm({"obs": data})
    out = norm({"obs": data}, peek=True)["obs"]
    assert abs(out.mean()) < 0.1 and 0.8 < out.std() < 1.2

    fs = FrameStackObservations(3)
    keys = [(0, "a")]
    o1 = fs({"obs": np.array([[1.0]])}, keys=keys)["obs"]
    assert o1.tolist() == [[1.0, 1.0, 1.0]]  # first frame repeats
    fs({"obs": np.array([[2.0]])}, keys=keys)
    o3 = fs({"obs": np.array([[3.0]])}, keys=keys)["obs"]
    assert o3.tolist() == [[1.0, 2.0, 3.0]]
    # peek must not advance history
    pk = fs({"obs": np.array([[9.0]])}, keys=keys, peek=True)["obs"]
    assert pk.tolist() == [[2.0, 3.0, 9.0]]
    o4 = fs({"obs": np.array([[4.0]])}, keys=keys)["obs"]
    assert o4.tolist() == [[2.0, 3.0, 4.0]]
    fs.drop(keys)
    o5 = fs({"obs": np.array([[7.0]])}, keys=keys)["obs"]
    assert o5.tolist() == [[7.0, 7.0, 7.0]]

    pipe = ConnectorPipelineV2([FlattenObservations(),
                                FrameStackObservations(2)])
    assert pipe.output_dim(4) == 8
    out = pipe({"obs": np.ones((2, 2, 2))}, keys=[(0, "x"), (0, "y")])
    assert out["obs"].shape == (2, 8)


def test_normalize_small_sample_std_unbiased():
    """Regression: _m2 must start at zeros (the additive identity), not
    ones — a ones seed adds a phantom unit of variance per feature and
    inflates small-sample std estimates (normalized outputs read low)."""
    from ray_tpu.rllib import NormalizeObservations

    norm = NormalizeObservations(clip=100.0)
    batch = np.array([[0.0], [2.0], [4.0]], np.float32)  # mean 2, m2 8
    norm({"obs": batch})
    st = norm.state()
    assert st["count"] == 3.0
    np.testing.assert_allclose(st["mean"], [2.0], atol=1e-9)
    # sum of squared deviations exactly; ones-seeded would report 9
    np.testing.assert_allclose(st["m2"], [8.0], atol=1e-6)
    # normalized output uses the unbiased sample std sqrt(8/2) = 2
    out = norm({"obs": batch}, peek=True)["obs"]
    np.testing.assert_allclose(out[:, 0], [-1.0, 0.0, 1.0], atol=1e-5)


def test_multi_agent_with_connector_pipeline(ray_start_4_cpus):
    """env→module connectors wired through the multi-agent runner: the
    module trains on stacked frames (obs_dim doubles) and learner
    sequences carry the PROCESSED obs."""
    from ray_tpu.rllib import (
        ConnectorPipelineV2,
        FlattenObservations,
        FrameStackObservations,
    )

    config = (
        PPOConfig()
        .environment(make_multi_agent("CartPole-v1"),
                     env_config={"num_agents": 2})
        .env_runners(
            num_env_runners=1, num_envs_per_env_runner=2,
            rollout_fragment_length=32,
            env_to_module_connector=lambda: ConnectorPipelineV2(
                [FlattenObservations(), FrameStackObservations(2)]
            ),
        )
        .training(lr=3e-3, minibatch_size=32, num_epochs=2)
        .multi_agent(policies={"shared"},
                     policy_mapping_fn=lambda aid, ep: "shared")
        .debugging(seed=11)
    )
    algo = config.build_algo()
    try:
        # CartPole obs is 4 -> stacked module spec must be 8
        assert algo.module_specs["shared"].obs_dim == 8
        r = algo.train()
        assert r["num_env_steps_sampled_lifetime"] > 0
        assert np.isfinite(r["learner"]["shared"]["policy_loss"])
        r2 = algo.train()
        assert r2["training_iteration"] == 2
    finally:
        algo.stop()
