"""Wire codec (PR 2): the two-marker frame format and its guarantees.

Covers the three contracts the fast path must keep:

1. round-trip fidelity over BOTH marker bytes — b"P" (stdlib pickle
   fast path) and b"C" (cloudpickle, used by payload blobs and as the
   frame fallback);
2. total-order preservation when `send` flushes messages buffered by
   `send_async` (the batch-frame path);
3. automatic cloudpickle fallback when stdlib pickle rejects a frame
   (a __main__-level lambda smuggled into a payload must arrive
   working, not raise at the sender).
"""

import pickle
import threading

import pytest

from ray_tpu._private import protocol as P
from ray_tpu._private.serialization import (
    MARKER_CLOUD,
    MARKER_PLAIN,
    dumps_frame,
    dumps_inline,
    loads_frame,
    loads_inline,
)

# ------------------------------------------------------------ round trips

# the shapes control frames actually take: dicts of primitives/bytes,
# nested containers, ids, resource maps, inline value blobs
PLAIN_PAYLOADS = [
    ("hello", {"role": "driver", "worker_id": "w" * 28, "pid": 4242}),
    (
        "submit_task",
        {
            "task_id": b"\x00" * 16,
            "fn_id": "f" * 40,
            "args_kind": "inline",
            "args_payload": b"C" + pickle.dumps(((1, 2), {})),
            "arg_deps": [b"a" * 16, b"b" * 16],
            "return_ids": [b"r" * 16],
            "resources": {"CPU": 1.0, "TPU": 0.0},
            "options": {"max_retries": 3, "name": None},
        },
    ),
    ("get", {"object_ids": [b"o" * 16] * 100, "timeout": None, "req_id": 7}),
    ("reply", {"req_id": 7, "values": [(b"o" * 16, "inline", b"x" * 4096)]}),
    ("batch", [("put", {"object_id": b"p" * 16, "kind": "shm",
                        "payload": "seg", "size": 2**20})] * 128),
    ("free", {"object_ids": []}),
    ("kv_put", {"key": b"k", "value": b"v" * 10_000, "overwrite": True,
                "req_id": 0}),
    # > 64 KiB frame: exercises the memoryview (zero-copy) loads branch
    ("put", {"object_id": b"q" * 16, "kind": "inline",
             "payload": b"z" * 200_000, "size": 200_000}),
]


@pytest.mark.parametrize("frame", PLAIN_PAYLOADS,
                         ids=[f[0] + str(i) for i, f in enumerate(PLAIN_PAYLOADS)])
def test_plain_frames_take_the_fast_path_and_round_trip(frame):
    blob = dumps_frame(frame)
    assert blob[:1] == MARKER_PLAIN
    assert loads_frame(blob) == frame


def test_cloudpickle_marker_round_trips_through_loads_frame():
    # dumps_inline output (payload blobs) must stay decodable by the
    # frame loader: both markers are pickle bytecode
    obj = ("msg", {"data": [1, 2, {"k": b"v"}]})
    blob = dumps_inline(obj)
    assert blob[:1] == MARKER_CLOUD
    assert loads_frame(blob) == obj
    assert loads_inline(blob) == obj


def test_loads_frame_rejects_unknown_marker():
    with pytest.raises(ValueError, match="codec marker"):
        loads_frame(b"X" + pickle.dumps(("m", {})))
    with pytest.raises(ValueError, match="codec marker"):
        loads_frame(b"")


def test_main_level_lambda_falls_back_to_cloudpickle():
    """A closure smuggled into a control payload: stdlib pickle raises
    at dump time (no importable qualname), so the codec must fall back
    to cloudpickle's by-value serialization — and the function must
    arrive runnable."""
    base = 10
    smuggled = lambda x: x + base  # noqa: E731
    smuggled.__module__ = "__main__"  # as if defined in a driver script
    frame = ("publish", {"channel": "c", "data": {"cb": smuggled}})
    blob = dumps_frame(frame)
    assert blob[:1] == MARKER_CLOUD
    msg_type, payload = loads_frame(blob)
    assert msg_type == "publish"
    assert payload["data"]["cb"](32) == 42


def test_retry_exceptions_classes_never_ride_a_frame_raw():
    """A __main__-defined exception class in retry_exceptions pickles by
    REFERENCE under stdlib pickle (dump succeeds, remote load fails) —
    so scheduling_options must blob it with cloudpickle before it
    reaches the frame codec, and the hub must unwrap the blob."""
    from ray_tpu.remote_function import scheduling_options

    class MyError(Exception):
        pass

    MyError.__module__ = "__main__"
    MyError.__qualname__ = "MyError"

    out = scheduling_options({"retry_exceptions": [MyError], "max_retries": 2})
    rex = out["retry_exceptions"]
    assert isinstance(rex, bytes) and rex[:1] == MARKER_CLOUD
    # the whole submit frame stays on the fast path...
    frame = ("submit_task", {"options": out, "task_id": b"t" * 16})
    blob = dumps_frame(frame)
    assert blob[:1] == MARKER_PLAIN
    # ...and the hub-side unwrap recovers a working class (by value)
    _mt, payload = loads_frame(blob)
    (cls,) = loads_inline(payload["options"]["retry_exceptions"])
    assert issubclass(cls, Exception)
    assert cls("x").args == ("x",)
    # a bare class (no list) is blobbed too — as a 1-tuple
    bare = scheduling_options({"retry_exceptions": MyError})["retry_exceptions"]
    assert isinstance(bare, bytes) and len(loads_inline(bare)) == 1
    # the blob is memoized: same class list, same bytes object per submit
    again = scheduling_options({"retry_exceptions": [MyError]})
    assert again["retry_exceptions"] is rex
    # retry_exceptions=True passes through untouched
    assert scheduling_options({"retry_exceptions": True})["retry_exceptions"] is True


def test_exception_instances_round_trip():
    from ray_tpu.exceptions import ActorDiedError

    blob = dumps_inline(ActorDiedError(msg="Actor is dead."))
    err = loads_inline(blob)
    assert isinstance(err, ActorDiedError)


# ------------------------------------------------- batch-frame ordering


class _FakeConn:
    """Captures send_bytes frames; recv_bytes blocks until closed (the
    reader thread parks on it and exits via EOFError on close())."""

    def __init__(self):
        self.frames = []
        self._closed = threading.Event()

    def send_bytes(self, blob):
        self.frames.append(blob)

    def recv_bytes(self):
        self._closed.wait()
        raise EOFError

    def close(self):
        self._closed.set()


@pytest.fixture
def stub_client(tmp_path, monkeypatch):
    from ray_tpu._private import client as client_mod

    conn = _FakeConn()
    monkeypatch.setattr(client_mod, "connect_hub", lambda addr: conn)
    c = client_mod.CoreClient(
        str(tmp_path / "hub.sock"), str(tmp_path), role="driver",
        worker_id="w" * 28,
    )
    yield c, conn
    c.close()


def _decode_stream(frames):
    """Flatten captured frames into the total (msg_type, payload) order
    the hub would observe."""
    out = []
    for blob in frames:
        msg_type, payload = loads_frame(blob)
        if msg_type == "batch":
            out.extend(payload)
        else:
            out.append((msg_type, payload))
    return out


def test_send_flushes_buffered_async_messages_in_order(stub_client):
    client, conn = stub_client
    start = len(conn.frames)
    for i in range(5):
        client.send_async("put", {"seq": i})
    client.send("get", {"seq": 5})  # must flush the 5 buffered puts first
    msgs = _decode_stream(conn.frames[start:])
    assert [m[0] for m in msgs] == ["put"] * 5 + ["get"]
    assert [m[1]["seq"] for m in msgs] == list(range(6))
    # every frame on the wire took the fast path
    assert all(f[:1] == MARKER_PLAIN for f in conn.frames)


def test_send_async_flushes_full_batches_in_order(stub_client):
    client, conn = stub_client
    start = len(conn.frames)
    for i in range(300):  # crosses the 128-message batch threshold twice
        client.send_async("put", {"seq": i})
    client.flush()
    msgs = _decode_stream(conn.frames[start:])
    assert [m[1]["seq"] for m in msgs] == list(range(300))


def test_inbound_dispatch_table_routes_reply_and_pubsub(stub_client):
    client, _conn = stub_client
    got = []
    client.subscriptions["chan"] = got.append

    fut_payload = {"req_id": 123, "ok": True}
    from concurrent.futures import Future

    fut = Future()
    with client._pending_lock:
        client._pending[123] = fut
    client._dispatch_inbound(P.REPLY, fut_payload)
    assert fut.result(timeout=1) == fut_payload

    # blob-wrapped pubsub (client.publish path) unwraps before the callback
    client._dispatch_inbound(
        P.PUBSUB_MSG, {"channel": "chan", "blob": dumps_inline({"x": 1})}
    )
    # hub-internal plain-data pubsub still works
    client._dispatch_inbound(P.PUBSUB_MSG, {"channel": "chan", "data": [4, 2]})
    assert got == [{"x": 1}, [4, 2]]

    # unknown types land on the executor queue
    client._dispatch_inbound("exec_task", {"task_id": b"t"})
    assert client.task_queue.get_nowait() == ("exec_task", {"task_id": b"t"})
