"""Platform services: client connect (Ray Client parity), runtime envs,
job submission, dashboard HTTP API, util.Queue, config table, memory
monitor, TPU pod helpers, durable workflows."""

import json
import os
import sys
import time

import numpy as np
import pytest

import ray_tpu


# ---------------------------------------------------------------- client
def test_client_connect_roundtrip(tmp_path):
    """A second process connects with init(address=...) and uses the
    cluster (tasks, actors, big results via object fetch)."""
    ctx = ray_tpu.init(num_cpus=2, max_workers=2, _tcp_hub=True)
    addr = ctx.address_info["address"]
    script = f"""
import sys; sys.path.insert(0, {json.dumps("/root/repo")})
import numpy as np
import ray_tpu
ray_tpu.init(address={json.dumps(addr)})
@ray_tpu.remote
def f(x):
    return x * 2
assert ray_tpu.get(f.remote(21)) == 42
@ray_tpu.remote
def big():
    return np.ones(300_000)  # shm on the cluster; fetched by the client
assert float(ray_tpu.get(big.remote()).sum()) == 300_000.0
@ray_tpu.remote
class C:
    def __init__(self): self.n = 0
    def inc(self): self.n += 1; return self.n
c = C.remote()
assert ray_tpu.get([c.inc.remote() for _ in range(3)]) == [1, 2, 3]
ray_tpu.shutdown()
print("CLIENT_OK")
"""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=120,
        )
        assert "CLIENT_OK" in out.stdout, out.stderr[-2000:]
        # the cluster survives the client's exit
        @ray_tpu.remote
        def alive():
            return True

        assert ray_tpu.get(alive.remote(), timeout=30)
    finally:
        ray_tpu.shutdown()


def test_client_large_object_plane(tmp_path):
    """Client object plane (reference: util/client/server/
    dataservicer.py chunked Put/GetObject): a shm-less client
    round-trips a >=256 MB ndarray — put chunk-streams into the
    head-node store where a cluster task consumes it zero-copy, and a
    task-produced array of the same size streams back on get."""
    ctx = ray_tpu.init(num_cpus=2, max_workers=2, _tcp_hub=True)
    addr = ctx.address_info["address"]
    script = f"""
import sys; sys.path.insert(0, {json.dumps("/root/repo")})
import numpy as np
import ray_tpu
ray_tpu.init(address={json.dumps(addr)})
n = 256 * 1024 * 1024
arr = np.arange(n, dtype=np.uint8)  # wraps mod 256; cheap to validate
ref = ray_tpu.put(arr)

@ray_tpu.remote
def consume(a):
    # runs on the cluster: maps the head-node segment directly
    return (a.nbytes, int(a[0]), int(a[-1]))

nbytes, first, last = ray_tpu.get(consume.remote(ref))
assert nbytes == n and first == 0 and last == (n - 1) % 256, (nbytes, first, last)

@ray_tpu.remote
def produce():
    return np.full(n, 7, dtype=np.uint8)

back = ray_tpu.get(produce.remote())
assert back.nbytes == n and back[0] == 7 and back[-1] == 7
ray_tpu.free([ref])
ray_tpu.shutdown()
print("CLIENT_BIG_OK")
"""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=300,
        )
        assert "CLIENT_BIG_OK" in out.stdout, out.stderr[-2000:]
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ runtime env
def test_runtime_env_env_vars(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "hello42"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_flag.remote(), timeout=60) == "hello42"
    # plain tasks run on env-less workers (isolation both ways)
    assert ray_tpu.get(read_plain.remote(), timeout=60) is None


def test_runtime_env_working_dir(ray_start_regular, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "my_module_xyz.py").write_text("VALUE = 'from_working_dir'\n")
    (pkg / "data.txt").write_text("payload")

    @ray_tpu.remote(runtime_env={"working_dir": str(pkg)})
    def use_pkg():
        import my_module_xyz  # importable: working_dir on sys.path

        with open("data.txt") as f:  # cwd == working_dir
            return my_module_xyz.VALUE, f.read()

    assert ray_tpu.get(use_pkg.remote(), timeout=60) == (
        "from_working_dir", "payload",
    )


def test_runtime_env_rejects_unsupported(ray_start_regular):
    @ray_tpu.remote(runtime_env={"container": {"image": "x"}})
    def f():
        return 1

    with pytest.raises(ValueError):
        f.remote()


def test_runtime_env_conda_requires_tooling(ray_start_regular, monkeypatch):
    """conda specs are accepted and materialize node-side (reference:
    _private/runtime_env/conda.py); without any conda binary the worker
    fails the task loudly instead of silently ignoring the env."""
    monkeypatch.delenv("CONDA_EXE", raising=False)

    @ray_tpu.remote(runtime_env={"conda": {"dependencies": ["pip"]}})
    def f():
        return 1

    with pytest.raises(Exception, match="conda"):
        ray_tpu.get(f.remote(), timeout=60)

    # invalid spec types still reject at submit time
    @ray_tpu.remote(runtime_env={"conda": 42})
    def g():
        return 1

    with pytest.raises(ValueError, match="conda"):
        g.remote()


def test_runtime_env_py_modules_dir(ray_start_regular, tmp_path):
    """py_modules (reference: _private/runtime_env/py_modules.py): a
    local package dir ships by content hash and lands on the worker's
    sys.path; a task WITHOUT the env must not see it (env-hash worker
    isolation)."""
    pkg = tmp_path / "rtpu_mod_demo"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("FLAVOR = 'from_py_modules'\n")
    (pkg / "extra.py").write_text("def val():\n    return 41 + 1\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(pkg)]})
    def with_env():
        import rtpu_mod_demo
        from rtpu_mod_demo import extra

        return rtpu_mod_demo.FLAVOR, extra.val()

    assert ray_tpu.get(with_env.remote(), timeout=120) == (
        "from_py_modules", 42,
    )

    @ray_tpu.remote
    def without_env():
        try:
            import rtpu_mod_demo  # noqa: F401

            return "visible"
        except ImportError:
            return "isolated"

    assert ray_tpu.get(without_env.remote(), timeout=60) == "isolated"


def test_runtime_env_py_modules_wheel(ray_start_regular, tmp_path):
    """A built wheel in py_modules installs through the offline pip
    machinery (reference: py_modules.py pip-installing wheel URIs)."""
    whl = _build_test_wheel(tmp_path, name="rtpu_pymod_whl",
                            value="'wheel_via_py_modules'")

    @ray_tpu.remote(runtime_env={"py_modules": [str(whl)]})
    def f():
        import rtpu_pymod_whl

        return rtpu_pymod_whl.VALUE

    assert ray_tpu.get(f.remote(), timeout=240) == "wheel_via_py_modules"


def _build_test_wheel(tmp_path, name="rtpu_demo_pkg", version="1.0",
                      value="'installed_from_wheel'"):
    """Hand-roll a minimal PEP-427 wheel (no egress, no build backend)."""
    import zipfile

    dist = f"{name}-{version}"
    whl = tmp_path / f"{name}-{version}-py3-none-any.whl"
    meta = f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n"
    wheel_meta = (
        "Wheel-Version: 1.0\nGenerator: ray_tpu-test\nRoot-Is-Purelib: "
        "true\nTag: py3-none-any\n"
    )
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}/__init__.py", f"VALUE = {value}\n")
        zf.writestr(f"{dist}.dist-info/METADATA", meta)
        zf.writestr(f"{dist}.dist-info/WHEEL", wheel_meta)
        zf.writestr(f"{dist}.dist-info/RECORD", "")
    return whl


def test_runtime_env_pip_local_wheel(ray_start_regular, tmp_path):
    """A task needing a package absent from the base env runs inside a
    materialized pip env (offline: the wheel ships through the KV).
    Reference: _private/runtime_env/pip.py + uri_cache.py."""
    whl = _build_test_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [str(whl)]})
    def use_pkg():
        import rtpu_demo_pkg

        return rtpu_demo_pkg.VALUE

    @ray_tpu.remote
    def without_env():
        try:
            import rtpu_demo_pkg  # noqa: F401

            return "leaked"
        except ImportError:
            return "isolated"

    assert ray_tpu.get(use_pkg.remote(), timeout=120) == (
        "installed_from_wheel"
    )
    # env-less workers must not see the installed package
    assert ray_tpu.get(without_env.remote(), timeout=60) == "isolated"
    # reuse: a second task with the same env hits the cached install
    assert ray_tpu.get(use_pkg.remote(), timeout=120) == (
        "installed_from_wheel"
    )


def test_runtime_env_uv_alias_and_env_vars_combo(ray_start_regular, tmp_path):
    whl = _build_test_wheel(tmp_path, name="rtpu_demo_uv", value="'uv_pkg'")

    @ray_tpu.remote(
        runtime_env={"uv": [str(whl)], "env_vars": {"COMBO": "yes"}}
    )
    def use_both():
        import rtpu_demo_uv

        return rtpu_demo_uv.VALUE, os.environ.get("COMBO")

    assert ray_tpu.get(use_both.remote(), timeout=120) == ("uv_pkg", "yes")


# ------------------------------------------------------------------ jobs
def test_job_submission_lifecycle(ray_start_regular, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job ran ok')\"",
    )
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == JobStatus.SUCCEEDED
    assert "job ran ok" in client.get_job_logs(job_id)
    assert any(j["job_id"] == job_id for j in client.list_jobs())

    bad = client.submit_job(entrypoint=f"{sys.executable} -c 'raise SystemExit(3)'")
    assert client.wait_until_finished(bad, timeout=60) == JobStatus.FAILED


def test_job_stop(ray_start_regular):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(60)'"
    )
    deadline = time.time() + 30
    while client.get_job_status(job_id) == JobStatus.PENDING:
        assert time.time() < deadline
        time.sleep(0.1)
    assert client.stop_job(job_id)
    assert client.wait_until_finished(job_id, timeout=30) == JobStatus.STOPPED


# -------------------------------------------------------------- dashboard
def test_dashboard_api(ray_start_regular):
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=18932).start()
    try:
        @ray_tpu.remote
        def noop():
            return 1

        ray_tpu.get(noop.remote())

        def get_json(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:18932{path}", timeout=10
            ) as r:
                return json.loads(r.read())

        status = get_json("/api/cluster_status")
        assert status["nodes"][0]["node_id"] == "node0"
        assert status["resources_total"]["CPU"] == 2.0
        assert isinstance(get_json("/api/actors"), list)
        assert any(
            e.get("state") == "FINISHED" for e in get_json("/api/tasks")
        )
        assert isinstance(get_json("/api/timeline"), list)
        with urllib.request.urlopen(
            "http://127.0.0.1:18932/metrics", timeout=10
        ) as r:
            assert r.status == 200
    finally:
        dash.stop()


# ------------------------------------------------------------------ queue
def test_util_queue(ray_start_regular):
    from ray_tpu.util.queue import Empty, Full, Queue

    q = Queue(maxsize=2)
    q.put("a")
    q.put("b")
    with pytest.raises(Full):
        q.put("c", block=False)
    assert q.qsize() == 2 and q.full()
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.empty()
    with pytest.raises(Empty):
        q.get(block=False)
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


# ----------------------------------------------------------------- config
def test_config_table_env_override(monkeypatch):
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "12345")
    from ray_tpu._private import config

    config.reload()
    assert config.RAY_TPU_CONFIG.memory_usage_threshold == 12345.0
    assert config.RAY_TPU_CONFIG.inline_object_threshold == 100 * 1024
    monkeypatch.delenv("RAY_TPU_MEMORY_USAGE_THRESHOLD")
    config.reload()


# --------------------------------------------------------- memory monitor
def test_memory_monitor_kills_hog(monkeypatch):
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", str(300 * 1024**2))
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_PERIOD_S", "0.2")
    ray_tpu.init(num_cpus=2, max_workers=2)
    try:
        from ray_tpu.exceptions import OutOfMemoryError, WorkerCrashedError

        @ray_tpu.remote(max_retries=0)
        def hog():
            ballast = bytearray(600 * 1024**2)  # far past the cap
            time.sleep(20)
            return len(ballast)

        with pytest.raises((OutOfMemoryError, WorkerCrashedError)):
            ray_tpu.get(hog.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ tpu helpers
def test_tpu_pod_helpers(monkeypatch):
    from ray_tpu.util.accelerators import tpu

    monkeypatch.setenv("TPU_NAME", "my-pod")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h1,h2,h3,h4")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-32")
    monkeypatch.setenv("RAY_TPU_NUM_TPUS", "8")
    assert tpu.get_current_pod_name() == "my-pod"
    assert tpu.get_current_pod_worker_count() == 4
    assert tpu.get_accelerator_type() == "v5litepod"
    assert tpu.get_num_tpu_chips_on_node() == 8


# -------------------------------------------------------------- workflows
def test_workflow_durable_resume(ray_start_regular, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path / "wf"))
    calls = tmp_path / "calls"
    calls.mkdir()

    @ray_tpu.remote
    def step_a(x):
        open(calls / "a", "a").write("x")
        return x + 1

    @ray_tpu.remote
    def step_b(x):
        open(calls / "b", "a").write("x")
        if not os.path.exists(calls / "b_ok"):
            open(calls / "b_ok", "w").close()
            raise RuntimeError("transient failure")
        return x * 10

    with InputNode() as inp:
        dag = step_b.bind(step_a.bind(inp))

    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf1", args=4)
    assert workflow.get_status("wf1") == "FAILED"
    # resume: step_a's durable result is NOT recomputed
    out = workflow.run(dag, workflow_id="wf1", args=4)
    assert out == 50
    assert workflow.get_status("wf1") == "SUCCEEDED"
    assert open(calls / "a").read() == "x"      # ran once
    assert open(calls / "b").read() == "xx"     # failed once, retried once
    assert {"workflow_id": "wf1", "status": "SUCCEEDED"} in workflow.list_all()


def test_workflow_parallel_branches(ray_start_4_cpus, tmp_path):
    """Independent DAG branches run concurrently (reference:
    workflow_executor.py keeps every ready node in flight): a diamond's
    two 1s branches overlap in wall-time instead of serializing."""
    import time

    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path / "wf"))

    @ray_tpu.remote
    def branch(x, tag):
        time.sleep(1.0)
        return (tag, time.time())

    @ray_tpu.remote
    def join(a, b):
        return (a, b)

    with InputNode() as inp:
        dag = join.bind(branch.bind(inp, "l"), branch.bind(inp, "r"))

    # warm two workers first: a cold spawn costs ~0.5-1.5s on this box
    # and would masquerade as serialization in the wall-time bound below
    ray_tpu.get([branch.remote(0, "warm_a"), branch.remote(0, "warm_b")])

    t0 = time.monotonic()
    (ltag, _), (rtag, _) = workflow.run(dag, workflow_id="wfp", args=0)
    elapsed = time.monotonic() - t0
    assert {ltag, rtag} == {"l", "r"}
    # sequential execution would be >= 2s; overlap keeps it well under
    assert elapsed < 1.9, f"branches serialized: {elapsed:.2f}s"


# ------------------------------------------------- small util components
def test_actor_group(ray_start_regular):
    from ray_tpu.util.actor_group import ActorGroup

    class Member:
        def __init__(self, base):
            self.base = base

        def add(self, x):
            return self.base + x

    grp = ActorGroup(Member, 3, init_args=(100,))
    assert grp.execute("add", 5) == [105, 105, 105]
    assert grp.execute_single(1, "add", 1) == 101
    grp.restart_actor(0)
    assert grp.execute("add", 2) == [102, 102, 102]
    grp.shutdown()


def test_multiprocessing_pool(ray_start_regular):
    from ray_tpu.util.multiprocessing import Pool

    def square(x):
        return x * x

    def add(a, b):
        return a + b

    with Pool() as pool:
        assert pool.map(square, range(6)) == [0, 1, 4, 9, 16, 25]
        assert pool.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(add, (5, 6)) == 11
        r = pool.map_async(square, [7])
        assert r.get(timeout=30) == [49]
        assert sorted(pool.imap_unordered(square, range(4))) == [0, 1, 4, 9]
    with pytest.raises(ValueError):
        pool.map(square, [1])


def test_state_api_lists_and_summaries(ray_start_regular):
    from ray_tpu.util import state

    @ray_tpu.remote
    def traced_fn():
        return 1

    @ray_tpu.remote
    class StateActor:
        def ping(self):
            return 1

    a = StateActor.remote()
    ray_tpu.get([a.ping.remote(), traced_fn.remote()])

    actors = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert actors and all(x["state"] == "ALIVE" for x in actors)
    assert state.list_nodes()[0]["node_id"] == "node0"
    summary = state.summarize_tasks()
    assert summary["total"] >= 1
    assert summary["by_state"].get("FINISHED", 0) >= 1
    assert "traced_fn" in summary["by_func_name"]
    assert state.summarize_actors()["by_state"].get("ALIVE", 0) >= 1
    assert state.summarize_objects()["total"] >= 1
    ray_tpu.kill(a)


def test_pool_windowed_lazy_imap(ray_start_regular):
    """processes bounds in-flight submission on the lazy paths; imap
    consumes more items than the window without hanging."""
    from ray_tpu.util.multiprocessing import Pool

    def ident(x):
        return x

    with Pool(processes=2) as pool:
        assert list(pool.imap(ident, range(9))) == list(range(9))
        assert sorted(pool.imap_unordered(ident, range(7))) == list(range(7))
        r = pool.map_async(ident, [1])
        assert r.get(timeout=30) == [1]
        assert r.ready() and r.successful()

    # successful() on an unfinished result raises (multiprocessing
    # contract) — use a result that can never complete
    from ray_tpu.util.multiprocessing import AsyncResult
    from ray_tpu.object_ref import ObjectRef
    from ray_tpu._private.ids import ObjectID

    ghost = AsyncResult([ObjectRef(ObjectID.generate())], single=False)
    with pytest.raises(ValueError):
        ghost.successful()


def test_dashboard_serves_html_index(ray_start_regular):
    """GET / returns the single-file UI over the JSON endpoints
    (reference: the dashboard frontend, minus React)."""
    import urllib.request

    from ray_tpu import dashboard as dmod

    d = dmod.Dashboard(port=18265).start()
    try:
        with urllib.request.urlopen("http://127.0.0.1:18265/", timeout=10) as r:
            html = r.read().decode()
        assert "ray_tpu dashboard" in html
        assert "/api/cluster_status" in html
    finally:
        d.stop()


def test_workflow_events_exactly_once(ray_start_regular, tmp_path):
    """wait_for_event (reference: workflow/event_listener.py): the
    workflow dies mid-wait, resumes after the event fires, and the
    checkpointed payload is never re-polled — exactly-once delivery
    even when a LATER node crashes after the event checkpoint."""
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    workflow.init(str(tmp_path / "wf"))
    evt_file = tmp_path / "the_event"
    polls = tmp_path / "polls"
    acks = tmp_path / "acks"

    class FileEvent(workflow.EventListener):
        def __init__(self, path, polls_path, acks_path):
            self.path = path
            self.polls_path = polls_path
            self.acks_path = acks_path

        def poll_for_event(self):
            import time as _t

            deadline = _t.monotonic() + 5
            while _t.monotonic() < deadline:
                if os.path.exists(self.path):
                    open(self.polls_path, "a").write("p")
                    return open(self.path).read()
                _t.sleep(0.1)
            raise TimeoutError("event never fired")

        def event_checkpointed(self, event):
            open(self.acks_path, "a").write("a")

    @ray_tpu.remote
    def consume(payload, x):
        if not os.path.exists(tmp_path / "late_ok"):
            open(tmp_path / "late_ok", "w").close()
            raise RuntimeError("crash after event checkpoint")
        return f"{payload}:{x}"

    with InputNode() as inp:
        ev = workflow.wait_for_event(
            FileEvent, str(evt_file), str(polls), str(acks)
        )
        dag = consume.bind(ev, inp)

    # 1) dies mid-wait (event absent -> listener times out)
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf_ev", args=7)
    assert workflow.get_status("wf_ev") == "FAILED"
    assert not polls.exists()

    # 2) the event fires; resume polls ONCE, checkpoints, acks — then
    # the downstream node crashes AFTER the checkpoint
    evt_file.write_text("hello")
    with pytest.raises(Exception):
        workflow.resume("wf_ev", dag, args=7)
    assert polls.read_text() == "p"
    assert acks.read_text() == "a"

    # 3) final resume: event NOT re-polled, downstream completes
    out = workflow.resume("wf_ev", dag, args=7)
    assert out == "hello:7"
    assert polls.read_text() == "p"  # still exactly one poll
    assert workflow.get_status("wf_ev") == "SUCCEEDED"


def test_dashboard_timeline_and_data_stats(ray_start_regular, tmp_path):
    """Dashboard renders what the cluster already collects (reference:
    dashboard/modules/state + data section): the chrome-trace timeline
    endpoint carries task spans, and dataset executions publish per-op
    stats that /api/data_stats serves."""
    import urllib.request

    import ray_tpu.data as rdata
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def work(x):
        return x * 2

    ray_tpu.get([work.remote(i) for i in range(4)])
    # a dataset execution publishes per-op stats to the KV
    ds = rdata.range(32).map(lambda r: {"v": r["id"] * 2})
    assert len(ds.take_all()) == 32

    dash = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{dash.port}"
        tl = json.loads(urllib.request.urlopen(base + "/api/timeline").read())
        spans = [e for e in tl if e.get("ph") == "X" and e.get("dur", 0) > 0]
        assert spans, "timeline must carry task spans"
        assert any(e["name"].startswith("work") for e in spans)

        stats = json.loads(
            urllib.request.urlopen(base + "/api/data_stats").read()
        )
        assert stats, "dataset execution must publish stats"
        stages = stats[-1]["stages"]
        assert any("map" in s["name"].lower() for s in stages)
        assert all("wall_s" in s and "blocks" in s for s in stages)

        html = urllib.request.urlopen(base + "/").read().decode()
        assert "timeline" in html and "data ops" in html
    finally:
        dash.stop()
