"""Runtime self-instrumentation: builtin ray_tpu_* hub/scheduler
metrics, the task-lifecycle latency breakdown, and the flight recorder
(list_state("events"), dashboard /api/events, crash dump)."""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics
from ray_tpu.util import state as state_api


def _wait_for(cond, timeout=10):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def _run_small_workload():
    @ray_tpu.remote
    def bump(x):
        return x + 1

    assert ray_tpu.get([bump.remote(i) for i in range(8)]) == list(range(1, 9))
    ref = ray_tpu.put({"k": "v"})
    assert ray_tpu.get(ref) == {"k": "v"}


# ------------------------------------------------------- builtin metrics
def test_builtin_metrics_present_after_workload(ray_start_regular):
    _run_small_workload()

    def enough():
        names = {
            m["name"] for m in metrics.snapshot()
            if m["name"].startswith("ray_tpu_")
        }
        return len(names) >= 10

    assert _wait_for(enough), sorted(
        {m["name"] for m in metrics.snapshot()}
    )
    snap = metrics.snapshot()
    by_name = {}
    for m in snap:
        by_name.setdefault(m["name"], []).append(m)
    # the acceptance floor: >= 10 distinct builtin series in the scrape
    builtin = [n for n in by_name if n.startswith("ray_tpu_")]
    assert len(builtin) >= 10, builtin
    # per-msg-type counters actually counted the workload's traffic;
    # the client auto-batcher may coalesce burst .remote() calls into
    # submit_tasks bulk frames, so accept either message type
    submit = [
        m for m in by_name["ray_tpu_hub_messages_total"]
        if ("type", "submit_task") in m["tags"]
        or ("type", "submit_tasks") in m["tags"]
    ]
    assert submit and sum(m["value"] for m in submit) >= 1
    # and the latency histogram observed the same messages
    lat = [
        m for m in by_name["ray_tpu_hub_handler_latency_seconds"]
        if ("type", "submit_task") in m["tags"]
        or ("type", "submit_tasks") in m["tags"]
    ]
    assert lat and sum(m["count"] for m in lat) >= 1
    assert sum(m["sum"] for m in lat) > 0
    placed = by_name["ray_tpu_scheduler_tasks_placed_total"][0]
    assert placed["value"] >= 8
    # everything renders through the one prometheus surface
    text = metrics.prometheus_text()
    prom_names = {
        line.split("{")[0].split(" ")[0]
        for line in text.splitlines()
        if line.startswith("ray_tpu_")
    }
    assert len(prom_names) >= 10, prom_names


def test_builtin_node_gauges_from_heartbeat(ray_start_regular):
    """The head self-samples the same gauges node agents report."""
    _run_small_workload()

    def gauges_up():
        snap = {
            m["name"]: m for m in metrics.snapshot()
            if m["name"].startswith("ray_tpu_node_")
        }
        return (
            snap.get("ray_tpu_node_rss_bytes", {}).get("value", 0) > 0
            and "ray_tpu_node_n_workers" in snap
            and "ray_tpu_node_chips_in_use" in snap
        )

    # heartbeat cadence is 2s; first sample lands within one period
    assert _wait_for(gauges_up, timeout=15), [
        m["name"] for m in metrics.snapshot()
    ]


# -------------------------------------------------- lifecycle breakdown
def test_summarize_tasks_latency_percentiles(ray_start_regular):
    @ray_tpu.remote
    def snooze():
        time.sleep(0.05)
        return 1

    ray_tpu.get([snooze.remote() for _ in range(4)])

    def done():
        s = state_api.summarize_tasks()
        return (s["run_time_s"] or {}).get("count", 0) >= 4

    assert _wait_for(done), state_api.summarize_tasks()
    s = state_api.summarize_tasks()
    qw, rt = s["queue_wait_s"], s["run_time_s"]
    for block in (qw, rt):
        assert block["p50"] <= block["p95"] <= block["p99"] <= block["max"]
        assert block["p50"] >= 0.0
    assert rt["p50"] >= 0.05  # the sleep is inside the run phase
    # raw monotonic stamps ride the task events themselves
    ev = next(
        e for e in state_api.list_tasks()
        if e.get("state") == "FINISHED" and e.get("name", "").startswith("snooze")
    )
    assert ev["t_submit"] <= ev["t_queued"] <= ev["t_scheduled"] <= ev["t_finished"]


def test_timeline_renders_queued_state_slices(ray_start_regular):
    @ray_tpu.remote
    def work():
        return 1

    ray_tpu.get([work.remote() for _ in range(3)])
    assert _wait_for(
        lambda: any(
            e.get("cat") == "task_state" for e in ray_tpu.timeline()
        )
    )
    tl = ray_tpu.timeline()
    queued = [e for e in tl if e.get("cat") == "task_state"]
    assert queued and all(e["ph"] == "X" for e in queued)
    assert all(e["name"].endswith("[queued]") for e in queued)
    assert all(e["args"]["transition"] == "SUBMITTED->RUNNING" for e in queued)


# --------------------------------------------------- flight recorder
def test_flight_recorder_basic_events(ray_start_regular):
    _run_small_workload()
    events = state_api.list_events()
    assert events, "hub_start should always be recorded"
    assert events[0]["kind"] == "hub_start"
    for e in events:
        assert {"seq", "ts", "kind"} <= set(e)
    # task give-up lands in the recorder
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise RuntimeError("x")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())
    assert _wait_for(
        lambda: any(
            e["kind"] == "task_failed" for e in state_api.list_events()
        )
    ), state_api.list_events()


def test_metric_type_conflict_records_event(ray_start_regular):
    c = metrics.Counter("dup_series_metric")
    c.inc(3)
    assert _wait_for(
        lambda: any(
            m["name"] == "dup_series_metric" for m in metrics.snapshot()
        )
    )
    g = metrics.Gauge("dup_series_metric")
    g.set(99)
    assert _wait_for(
        lambda: any(
            e["kind"] == "metric_type_conflict"
            and e["name"] == "dup_series_metric"
            for e in state_api.list_events()
        )
    ), state_api.list_events()
    # first-wins: the entry keeps its original type
    m = next(
        m for m in metrics.snapshot() if m["name"] == "dup_series_metric"
    )
    assert m["type"] == "counter"


def test_flight_recorder_dump(ray_start_regular, tmp_path):
    _run_small_workload()
    from ray_tpu._private import worker as _worker

    path = _worker._hub.dump_flight_recorder("test")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "test"
    assert {"events", "metrics", "nodes", "workers", "tasks"} <= set(doc)
    assert any(e["kind"] == "hub_start" for e in doc["events"])
    assert any(
        m["name"].startswith("ray_tpu_") for m in doc["metrics"]
    )
    assert doc["nodes"][0]["node_id"] == "node0"


def test_node_death_lands_in_flight_recorder(shutdown_only):
    """The acceptance-criteria scenario: an induced node death must be
    reconstructable from list_state("events") alone."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_num_cpus=2)
    try:
        node = cluster.add_node(num_cpus=1, resources={"doomed": 1.0})
        assert _wait_for(
            lambda: any(
                e["kind"] == "node_up" and e["node_id"] == node.node_id
                for e in state_api.list_events()
            )
        ), state_api.list_events()
        cluster.remove_node(node)
        assert _wait_for(
            lambda: any(
                e["kind"] == "node_down" and e["node_id"] == node.node_id
                for e in state_api.list_events()
            )
        ), state_api.list_events()
        down = next(
            e for e in state_api.list_events()
            if e["kind"] == "node_down" and e["node_id"] == node.node_id
        )
        assert down["ts"] > 0 and "hostname" in down
    finally:
        cluster.shutdown()


# ------------------------------------------------------- metrics bugfixes
def test_histogram_rejects_bad_boundaries():
    for bad in ([1.0, 0.5, 2.0], [0.5, 0.5, 1.0], [-1.0, 1.0], [0.0, 1.0]):
        with pytest.raises(ValueError):
            metrics.Histogram("h", boundaries=bad)
    # sorted positive boundaries still construct
    h = metrics.Histogram("h", boundaries=[0.1, 1.0, 10.0])
    assert h.boundaries == [0.1, 1.0, 10.0]


def test_prometheus_escaping_and_name_sanitization(ray_start_regular):
    c = metrics.Counter("weird metric-name", description="d", tag_keys=("q",))
    c.inc(1, tags={"q": 'a"b\\c\nd'})
    assert _wait_for(
        lambda: any(
            m["name"] == "weird metric-name" for m in metrics.snapshot()
        )
    )
    text = metrics.prometheus_text()
    # names clamp to [a-zA-Z_:][a-zA-Z0-9_:]*
    assert "weird_metric_name" in text
    assert "weird metric-name" not in text
    # label values escape backslash, quote, and newline
    assert 'q="a\\"b\\\\c\\nd"' in text
    assert "\nd\"" not in text  # the raw newline must not survive
    # label NAMES are stricter than metric names: no ':' allowed
    g = metrics.Gauge("colon_gauge", tag_keys=("app:env",))
    g.set(1.0, tags={"app:env": "prod"})
    assert _wait_for(
        lambda: any(m["name"] == "colon_gauge" for m in metrics.snapshot())
    )
    text = metrics.prometheus_text()
    assert 'colon_gauge{app_env="prod"}' in text
    assert "app:env=" not in text


def test_prometheus_no_raw_newlines_in_series(ray_start_regular):
    g = metrics.Gauge("nl_gauge", tag_keys=("t",))
    g.set(1.0, tags={"t": "line1\nline2"})
    assert _wait_for(
        lambda: any(m["name"] == "nl_gauge" for m in metrics.snapshot())
    )
    for line in metrics.prometheus_text().splitlines():
        if line.startswith("nl_gauge"):
            assert 'line1\\nline2' in line


# ------------------------------------------------------------ dashboard
def test_dashboard_metrics_timeline_events_endpoints(ray_start_regular):
    import urllib.request

    from ray_tpu.dashboard import Dashboard

    _run_small_workload()
    dash = Dashboard(port=0).start()
    try:
        base = f"http://127.0.0.1:{dash.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert r.status == 200
            body = r.read().decode()
        assert "ray_tpu_hub_messages_total" in body
        with urllib.request.urlopen(base + "/api/timeline", timeout=10) as r:
            assert r.status == 200
            tl = json.loads(r.read())
        assert isinstance(tl, list) and all(e["ph"] == "X" for e in tl)
        with urllib.request.urlopen(base + "/api/events", timeout=10) as r:
            assert r.status == 200
            events = json.loads(r.read())
        assert isinstance(events, list) and events
        assert all("kind" in e and "ts" in e and "seq" in e for e in events)
        assert any(e["kind"] == "hub_start" for e in events)
    finally:
        dash.stop()


# ---------------------------------------- exposition-format edge cases
# (pure rendering tests: snapshot() is monkeypatched, no cluster)
def _fake_snapshot(monkeypatch, rows):
    monkeypatch.setattr(metrics, "snapshot", lambda: rows)


def _gauge_row(name, value=1.0, tags=(), description=""):
    return {"name": name, "type": "gauge", "description": description,
            "tags": tuple(tags), "value": value, "sum": 0.0, "count": 0,
            "buckets": []}


def test_exposition_label_value_escape_round_trip(monkeypatch):
    """Escaping must be invertible: a parser applying the exposition
    format's unescape rules recovers the original tag value exactly."""
    nasty = 'quo"te back\\slash new\nline'
    _fake_snapshot(
        monkeypatch, [_gauge_row("rt_g", tags=(("k", nasty),))]
    )
    text = metrics.prometheus_text()
    line = next(ln for ln in text.splitlines() if ln.startswith("rt_g{"))
    raw = line[line.index('k="') + 3:line.rindex('"')]
    # the exposition unescape: \\ -> \, \" -> ", \n -> newline —
    # placeholder-swap \\ first so a backslash that escapes an escape
    # is not double-consumed
    unescaped = (
        raw.replace("\\\\", "\x00")
        .replace('\\"', '"')
        .replace("\\n", "\n")
        .replace("\x00", "\\")
    )
    assert unescaped == nasty
    assert "\n" not in line  # the raw newline never leaks into a series


def test_exposition_sanitize_collision_single_type_line(monkeypatch):
    """Two raw names that sanitize to the same exposition name must not
    emit duplicate ``# TYPE`` lines — Prometheus rejects a scrape with
    a repeated TYPE for one name; first-wins, both series still render."""
    assert metrics._sanitize_name("hub.frames") == "hub_frames"
    assert metrics._sanitize_name("hub-frames") == "hub_frames"
    _fake_snapshot(monkeypatch, [
        _gauge_row("hub.frames", 1.0, (("src", "a"),), description="da"),
        _gauge_row("hub-frames", 2.0, (("src", "b"),), description="db"),
    ])
    text = metrics.prometheus_text()
    type_lines = [
        ln for ln in text.splitlines()
        if ln.startswith("# TYPE hub_frames ")
    ]
    assert len(type_lines) == 1
    assert 'hub_frames{src="a"} 1.0' in text
    assert 'hub_frames{src="b"} 2.0' in text


def test_exposition_histogram_buckets_cumulative_vs_inf(monkeypatch):
    """_bucket series must be CUMULATIVE (le-ordered running sums) and
    the +Inf bucket must equal the total observation count — including
    observations above the largest boundary, which live in no finite
    bucket."""
    _fake_snapshot(monkeypatch, [{
        "name": "lat", "type": "histogram", "description": "",
        "tags": (),
        "value": 0.0, "sum": 12.5, "count": 7,
        # per-bucket (non-cumulative) counts as the hub stores them;
        # 2 observations fell past the last bound (2+3 < 7)
        "buckets": [[0.1, 2], [1.0, 3]],
    }])
    text = metrics.prometheus_text()
    assert 'lat_bucket{le="0.1"} 2' in text
    assert 'lat_bucket{le="1.0"} 5' in text        # 2+3, cumulative
    assert 'lat_bucket{le="+Inf"} 7' in text       # total, not 5
    assert "lat_sum 12.5" in text
    assert "lat_count 7" in text
    # cumulativity holds mechanically: counts never decrease in le order
    counts = [
        int(ln.rsplit(" ", 1)[1])
        for ln in text.splitlines() if ln.startswith("lat_bucket")
    ]
    assert counts == sorted(counts)


def test_prometheus_text_degrades_when_hub_down(monkeypatch):
    """/metrics during hub teardown/partition: last-known exposition
    (or an empty one) — never an exception out of the scrape handler."""
    _fake_snapshot(monkeypatch, [_gauge_row("up_g", 3.0)])
    good = metrics.prometheus_text()
    assert "up_g 3.0" in good

    def boom():
        raise ConnectionError("hub is gone")

    monkeypatch.setattr(metrics, "snapshot", boom)
    assert metrics.prometheus_text() == good  # last-known, verbatim

    # a process that NEVER scraped successfully serves empty, not a 500
    monkeypatch.setattr(metrics, "_last_exposition", "")
    assert metrics.prometheus_text() == ""
