"""Model zoo tests (CPU, tiny configs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(jax.random.PRNGKey(0), llama.LLAMA_TINY)


def test_forward_shapes(tiny_params):
    cfg = llama.LLAMA_TINY
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = llama.forward(tiny_params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_param_count_matches(tiny_params):
    n = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(tiny_params))
    assert n == llama.param_count(llama.LLAMA_TINY)


def test_loss_near_uniform_at_init(tiny_params):
    cfg = llama.LLAMA_TINY
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size)
    loss = llama.loss_fn(tiny_params, {"tokens": tokens}, cfg)
    # random init ⇒ loss ≈ ln(vocab)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.5


def test_loss_mask(tiny_params):
    cfg = llama.LLAMA_TINY
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, cfg.vocab_size)
    mask = jnp.ones_like(tokens, jnp.float32)
    full = llama.loss_fn(tiny_params, {"tokens": tokens, "mask": mask}, cfg)
    half_mask = mask.at[:, 9:].set(0.0)
    half = llama.loss_fn(tiny_params, {"tokens": tokens, "mask": half_mask}, cfg)
    assert full.shape == () and half.shape == ()
    assert float(full) != float(half)


def test_causality(tiny_params):
    """Changing a future token must not change past logits."""
    cfg = llama.LLAMA_TINY
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, cfg.vocab_size)
    logits_a = llama.forward(tiny_params, tokens, cfg)
    tokens_b = tokens.at[0, -1].set((tokens[0, -1] + 1) % cfg.vocab_size)
    logits_b = llama.forward(tiny_params, tokens_b, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_a[0, :-1]), np.asarray(logits_b[0, :-1]),
        rtol=2e-2, atol=2e-2,
    )


def test_gqa_vs_mha_shapes():
    cfg = llama.LlamaConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=4, n_kv_heads=4,
        ffn_dim=64, remat=False, dtype=jnp.float32,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    out = llama.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)
    assert out.shape == (1, 8, 64)


def test_training_reduces_loss():
    import optax
    cfg = llama.LlamaConfig(
        vocab_size=32, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_dim=64, remat=False, dtype=jnp.float32,
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 17), 0, 32)
    batch = {"tokens": tokens}

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(llama.loss_fn)(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for i in range(30):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first - 0.5, (first, float(loss))


def test_flash_attention_matches_xla():
    from ray_tpu.ops.attention import flash_attention
    from ray_tpu.models.llama import _attention_xla, LlamaConfig
    cfg = LlamaConfig(n_heads=4, n_kv_heads=2, dim=32)
    rng = jax.random.PRNGKey(0)
    B, S, H, KVH, hd = 2, 64, 4, 2, 16
    q = jax.random.normal(rng, (B, S, H, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KVH, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KVH, hd), jnp.float32)
    ref = _attention_xla(q, k, v, cfg)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_kv=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_attention_in_model():
    import dataclasses
    cfg = dataclasses.replace(llama.LLAMA_TINY, attention_impl="flash", dtype=jnp.float32)
    cfg_ref = dataclasses.replace(llama.LLAMA_TINY, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)
    a = llama.forward(params, tokens, cfg)
    b = llama.forward(params, tokens, cfg_ref)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3)


def test_unknown_attention_impl_raises():
    import dataclasses
    cfg = dataclasses.replace(llama.LLAMA_TINY, attention_impl="bogus")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="attention_impl"):
        llama.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)


def test_ring_attention_impl_matches_xla():
    """attention_impl='ring' without a seq mesh falls back to flash and
    matches the xla einsum path; with a seq mesh it runs the ring (the
    multi-axis case is exercised by __graft_entry__.dryrun_multichip)."""
    import dataclasses

    cfg = dataclasses.replace(llama.LLAMA_TINY, dtype=jnp.float32)
    cfg_ring = dataclasses.replace(cfg, attention_impl="ring")
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    ref = llama.forward(params, toks, cfg)
    out = llama.forward(params, toks, cfg_ring)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-3)
