"""Multi-tenant gang scheduler (fairsched): priority queues, fair-share
quotas, slice-aware preemption.

Unit tests drive the policy engine directly with a deterministic fake
clock (no wall-time dependence in any priority/fair-share assertion);
the end-to-end tests run the real hub on a fake (CPU-virtual) cluster:
a contended 50/50-quota cluster converges to equal chip-time, and a
priority-10 SLICE reservation preempts a priority-0 gang that later
completes through the existing retry/restart machinery.
"""

import itertools
import json
import time
from collections import deque
from types import SimpleNamespace

import pytest

import ray_tpu
from ray_tpu import JobConfig
from ray_tpu._private.fairsched import FairScheduler
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util import state as state_api


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


_seq = itertools.count()


def _spec(tenant, resources=None, priority=0, job_id=None):
    opts = {"tenant": tenant, "priority": priority}
    if job_id:
        opts["job_id"] = job_id
    return SimpleNamespace(
        task_id=b"t%06d" % next(_seq),
        resources=dict(resources or {"CPU": 1.0}),
        options=opts,
        is_actor_create=False,
    )


def _class_key(tenant, priority=0):
    # mirrors Hub._sched_class's tail: (..., tenant, priority)
    return ((("CPU", 1.0),), None, None, "", tenant, priority)


# ------------------------------------------------------------ policy units


def test_priority_orders_classes_before_fair_share():
    clock = FakeClock()
    fs = FairScheduler(clock=clock.now)
    fs.register_job("ja", tenant="a", priority=0, conn_id=1)
    fs.register_job("jb", tenant="b", priority=5, conn_id=1)
    keys = [_class_key("a", 0), _class_key("b", 5)]
    keys.sort(key=fs.class_order_key)
    assert keys[0][-2] == "b"  # higher priority first, regardless of usage


def test_fair_share_deficit_alternates_with_fake_clock():
    """One chip, two equal tenants with infinite backlog: the deficit
    ordering must strictly alternate dispatch (deterministic: all time
    comes from the fake clock)."""
    clock = FakeClock()
    fs = FairScheduler(clock=clock.now)
    fs.register_job("ja", tenant="a", conn_id=1)
    fs.register_job("jb", tenant="b", conn_id=1)
    order = []
    for _ in range(10):
        tenant = min(
            ("a", "b"), key=lambda tn: fs.class_order_key(_class_key(tn))
        )
        order.append(tenant)
        s = _spec(tenant)
        assert fs.admit(s)
        fs.charge_dispatch(s)
        clock.advance(1.0)
        fs.settle(s.task_id)
        fs.release_admission(s.task_id)
    # a starts (tie -> insertion order), then strict alternation
    assert order == ["a", "b"] * 5


def test_fifty_fifty_quota_converges_on_contended_fake_cluster():
    """Acceptance: two tenants under 50/50 quota on a contended fake
    4-chip cluster — chip-time per tenant converges within 20% of
    equal share. Fully simulated on the fake clock."""
    clock = FakeClock()
    fs = FairScheduler(clock=clock.now)
    fs.register_job("ja", tenant="a", quota={"TPU": 2}, conn_id=1)
    fs.register_job("jb", tenant="b", quota={"TPU": 2}, conn_id=1)
    backlog = {
        tn: deque(
            _spec(tn, {"TPU": 1.0}, job_id="j" + tn) for _ in range(60)
        )
        for tn in ("a", "b")
    }
    free = 4
    running = []  # [end_time, spec]
    chip_seconds = {"a": 0.0, "b": 0.0}
    runnable: deque = deque()
    for _ in range(400):
        runnable.extend(fs.pop_admissible())
        for tn in ("a", "b"):
            while backlog[tn]:
                s = backlog[tn].popleft()
                if fs.admit(s):
                    runnable.append(s)
                else:
                    break  # parked inside the engine (pending_quota)
        ordered = sorted(
            runnable,
            key=lambda s: fs.class_order_key(
                _class_key(s.options["tenant"])
            ),
        )
        runnable = deque(ordered)
        while runnable and free > 0:
            s = runnable.popleft()
            free -= 1
            fs.charge_dispatch(s)
            running.append([clock.t + 1.0, s])
        if not running:
            break
        nxt = min(end for end, _ in running)
        clock.advance(nxt - clock.t)
        done = [r for r in running if r[0] <= clock.t + 1e-9]
        running = [r for r in running if r[0] > clock.t + 1e-9]
        for _, s in done:
            free += 1
            chip_seconds[s.options["tenant"]] += 1.0
            fs.settle(s.task_id)
            fs.release_admission(s.task_id)
    total = sum(chip_seconds.values())
    assert total == 120.0  # every queued task ran
    for tn in ("a", "b"):
        assert abs(chip_seconds[tn] / total - 0.5) <= 0.2 * 0.5


def test_quota_admission_parks_and_readmits():
    clock = FakeClock()
    fs = FairScheduler(clock=clock.now)
    fs.register_job("j", tenant="t", quota={"CPU": 2}, conn_id=1)
    s1, s2, s3 = (_spec("t") for _ in range(3))
    assert fs.admit(s1) and fs.admit(s2)
    assert not fs.admit(s3)  # over quota: parked
    assert fs.parked_count() == 1
    assert fs.pop_admissible() == []  # still over
    fs.release_admission(s1.task_id)
    assert fs.pop_admissible() == [s3]  # room freed -> re-admitted FIFO
    assert fs.parked_count() == 0
    # idempotent: double release must not under-count
    fs.release_admission(s1.task_id)
    fs.release_admission(s2.task_id)
    fs.release_admission(s3.task_id)
    assert all(
        v <= 1e-9 for v in fs.tenants["t"].admitted.values()
    )


def test_infeasible_request_rejected_loudly():
    """A request bigger than the quota itself can never be admitted:
    admit() raises instead of parking it forever (and wedging the
    tenant's FIFO queue behind it)."""
    from ray_tpu._private.fairsched import QuotaInfeasibleError

    fs = FairScheduler()
    fs.register_job("j", tenant="t", quota={"TPU": 4}, conn_id=1)
    with pytest.raises(QuotaInfeasibleError):
        fs.admit(_spec("t", {"TPU": 8}))
    assert fs.parked_count() == 0
    # a later quota drop strands parked-but-now-infeasible work:
    # pop_infeasible surfaces it for loud failure
    big = _spec("t", {"TPU": 4})
    small = _spec("t", {"TPU": 4})
    assert fs.admit(big)
    assert not fs.admit(small)  # parked (feasible, just contended)
    fs.register_job("j", tenant="t", quota={"TPU": 2}, conn_id=1)
    assert fs.pop_infeasible("t") == [small]
    assert fs.parked_count() == 0


def test_quota_tristate_on_reregistration():
    fs = FairScheduler()
    fs.register_job("j1", tenant="t", quota={"CPU": 2}, conn_id=1)
    fs.register_job("j2", tenant="t", quota=None, conn_id=1)
    assert fs.tenants["t"].quota == {"CPU": 2.0}  # None = no opinion
    fs.register_job("j3", tenant="t", quota={}, conn_id=1)
    assert fs.tenants["t"].quota == {}  # {} lifts the cap


def test_drop_conn_prunes_job_registry():
    fs = FairScheduler()
    fs.register_job("j1", tenant="a", conn_id=11)
    fs.register_job("j2", tenant="b", conn_id=22)
    assert fs.drop_conn(11) == ["j1"]
    assert list(fs.jobs) == ["j2"]
    # idle tenant of the dropped job is gone too (no admitted/parked)
    assert "a" not in fs.tenants and "b" in fs.tenants
    # a tenant still holding parked work survives its registering conn
    fs.tenants["b"].quota = {"CPU": 1}
    running = _spec("b", {"CPU": 1})
    parked = _spec("b", {"CPU": 1})
    assert fs.admit(running)
    assert not fs.admit(parked)  # feasible but contended: parks
    fs.drop_conn(22)
    assert "b" in fs.tenants and fs.parked_count() == 1


def test_settle_pops_running_even_after_tenant_drop():
    """Driver churn must not leak fair-share intervals: settle() pops
    the _running entry even when the tenant was already pruned."""
    fs = FairScheduler()
    fs.register_job("j", tenant="x", conn_id=1)
    s = _spec("x")
    assert fs.admit(s)
    fs.charge_dispatch(s)
    assert s.task_id in fs._running
    fs.drop_conn(1)  # tenant pruned (no quota, nothing parked)
    assert "x" not in fs.tenants
    fs.settle(s.task_id)
    assert not fs._running


def _pg(priority, seq, chips, bundles=None, node="node0"):
    return SimpleNamespace(
        priority=priority, seq=seq,
        bundle_chips=[tuple(range(chips))] if chips else [],
        bundle_nodes=[node] if chips else [],
        bundles=bundles or [{"TPU": float(chips)}],
    )


def test_preemption_victim_selection():
    fs = FairScheduler()
    low_old = _pg(0, 1, 4)
    low_new = _pg(0, 2, 4)
    mid = _pg(5, 3, 4)
    high = _pg(9, 4, 4)
    nodes = {"node0": {}}
    # need 4 chips, 0 free: one gang suffices — lowest priority bleeds
    # first, and within a priority the NEWEST gang dies first
    pgs, tasks = fs.preemption_victims(
        10, 4, {"TPU": 4.0}, {"TPU": 4.0},
        [low_old, low_new, mid, high], [], {"node0": 0}, nodes)
    assert pgs == [low_new] and tasks == []
    # a bigger gap takes whole gangs in order, never partial
    pgs, _ = fs.preemption_victims(
        10, 12, {"TPU": 4.0}, {"TPU": 12.0},
        [low_old, low_new, mid, high], [], {"node0": 0}, nodes)
    assert pgs == [low_new, low_old, mid]
    # equal/higher priority is never a victim; infeasible -> no-op
    pgs, tasks = fs.preemption_victims(
        5, 4, {"TPU": 4.0}, {"TPU": 4.0}, [mid, high], [],
        {"node0": 0}, nodes)
    assert pgs == [] and tasks == []


def test_preemption_is_node_aware():
    """Two 2-chip victims on DIFFERENT hosts cannot seat a 4-chip
    single-node bundle: shedding them would be work lost for naught,
    so nothing is preempted."""
    fs = FairScheduler()
    va = _pg(0, 1, 2, node="nodeA")
    vb = _pg(0, 2, 2, node="nodeB")
    nodes = {"nodeA": {}, "nodeB": {}}
    pgs, tasks = fs.preemption_victims(
        10, 4, {"TPU": 4.0}, {"TPU": 4.0}, [va, vb], [],
        {"nodeA": 0, "nodeB": 0}, nodes)
    assert pgs == [] and tasks == []
    # same victims CAN seat two 2-chip bundles (one per host)
    pgs, _ = fs.preemption_victims(
        10, 4, {"TPU": 2.0}, {"TPU": 4.0}, [va, vb], [],
        {"nodeA": 0, "nodeB": 0}, nodes)
    assert set(id(p) for p in pgs) == {id(va), id(vb)}


def test_non_slice_tpu_gangs_are_preemptable():
    """PACK/SPREAD TPU gangs have no bundle_chips (only SLICE reserves
    specific chips), but killing them still frees their chips — the
    feasibility model must credit the bundle's TPU request."""
    fs = FairScheduler()
    pack_gang = SimpleNamespace(
        priority=0, seq=1, bundle_chips=[],  # non-SLICE: no chunks
        bundle_nodes=["node0"], bundles=[{"TPU": 8.0}],
    )
    pgs, tasks = fs.preemption_victims(
        10, 8, {"TPU": 8.0}, {"TPU": 8.0}, [pack_gang], [],
        {"node0": 0}, {"node0": {}})
    assert pgs == [pack_gang] and tasks == []


def test_single_task_victims_bleed_before_gangs():
    """Within a priority, one task retry loses less work than a whole
    gang restart: the task is taken first when it alone closes the
    gap."""
    fs = FairScheduler()
    gang = _pg(0, 1, 4)
    worker = SimpleNamespace(pinned_chips=(0, 1, 2, 3), node_id="node0")
    spec = SimpleNamespace(
        task_id=b"tv", resources={"TPU": 4.0},
        options={"tenant": "t", "priority": 0}, is_actor_create=False,
    )
    pgs, tasks = fs.preemption_victims(
        10, 4, {"TPU": 4.0}, {"TPU": 4.0}, [gang], [(worker, spec)],
        {"node0": 0}, {"node0": {}})
    assert pgs == [] and tasks == [(worker, spec)]


def test_usage_decays_and_newcomers_start_at_baseline():
    """A tenant's hour of solo usage must not starve it once a
    competitor registers: usage decays (10-min half-life) and a new
    tenant enters at the lowest incumbent's level, not zero."""
    clock = FakeClock()
    fs = FairScheduler(clock=clock.now)
    fs.register_job("ja", tenant="a", conn_id=1)
    s = _spec("a", {"TPU": 4.0})
    assert fs.admit(s)
    fs.charge_dispatch(s)
    clock.advance(3600.0)  # tenant a runs alone for an hour
    fs.settle(s.task_id)
    fs.release_admission(s.task_id)
    fs.register_job("jb", tenant="b", conn_id=1)
    ua = fs.tenants["a"].live_usage(clock.now())
    ub = fs.tenants["b"].live_usage(clock.now())
    # newcomer starts at the incumbent's level: no catch-up monopoly
    assert ub == pytest.approx(ua)
    ordered = sorted(("a", "b"), key=lambda tn: fs.class_order_key(_class_key(tn)))
    assert ordered[0] == "a"  # tie broken stably, not b-first-for-an-hour
    # and the history itself fades: two half-lives -> a quarter left
    clock.advance(1200.0)
    assert fs.tenants["a"].live_usage(clock.now()) == pytest.approx(
        ua * 0.25
    )


def test_pg_reservations_count_against_quota():
    """Placement-group reservations hold chips exclusively, so they
    charge the tenant's quota at creation (fail-fast when over), and
    tasks placed INTO the PG are exempt (no double counting)."""
    fs = FairScheduler()
    fs.register_job("j", tenant="t", quota={"TPU": 4}, conn_id=1)
    assert fs.charge_reservation(b"pg1", "t", {"TPU": 4.0}) is None
    err = fs.charge_reservation(b"pg2", "t", {"TPU": 2.0})
    assert err is not None and "quota" in err
    # a task running inside the PG does not re-charge the quota
    inside = SimpleNamespace(
        task_id=b"ti", resources={"TPU": 2.0},
        options={"tenant": "t", "placement_group": (b"pg1", 0)},
        is_actor_create=False,
    )
    assert fs.admit(inside)
    # removal releases the reservation; the next PG fits again
    fs.release_admission(b"pg1")
    assert fs.charge_reservation(b"pg2", "t", {"TPU": 2.0}) is None


def test_release_admission_prunes_orphaned_tenants():
    """A conn dropping with work in flight keeps its tenant only until
    that work finishes — then the tenant (and its accounting) goes."""
    fs = FairScheduler()
    fs.register_job("j", tenant="t", quota={"CPU": 2}, conn_id=1)
    s = _spec("t")
    assert fs.admit(s)
    fs.drop_conn(1)
    assert "t" in fs.tenants  # admitted work still in flight
    fs.release_admission(s.task_id)
    assert "t" not in fs.tenants  # fully idle + job-less: pruned


def test_preemption_requires_resource_colocation():
    """The largest bundle's chips AND its other resources must land on
    one node: freeing CPU on a different host than the chips does not
    make {TPU:4, CPU:8} schedulable, so nothing is preempted."""
    fs = FairScheduler()
    chip_victim = _pg(0, 1, 4, bundles=[{"TPU": 4.0}], node="nodeA")
    cpu_victim = SimpleNamespace(
        priority=0, seq=2, bundle_chips=[()], bundle_nodes=["nodeB"],
        bundles=[{"CPU": 8.0}],
    )
    need = {"TPU": 4.0, "CPU": 8.0}
    nodes = {"nodeA": {"CPU": 0.0}, "nodeB": {"CPU": 0.0}}
    pgs, tasks = fs.preemption_victims(
        10, 4, need, need, [chip_victim, cpu_victim], [],
        {"nodeA": 0, "nodeB": 0}, nodes)
    assert pgs == [] and tasks == []
    # with the CPU freed on the SAME node as the chips, it works
    cpu_victim_a = SimpleNamespace(
        priority=0, seq=2, bundle_chips=[()], bundle_nodes=["nodeA"],
        bundles=[{"CPU": 8.0}],
    )
    pgs, _ = fs.preemption_victims(
        10, 4, need, need, [chip_victim, cpu_victim_a], [],
        {"nodeA": 0, "nodeB": 0}, nodes)
    assert set(id(p) for p in pgs) == {id(chip_victim), id(cpu_victim_a)}


def test_new_arrivals_do_not_bypass_parked_queue():
    """FIFO re-admission: once a big task is parked, later small tasks
    from the same tenant park behind it instead of slipping into every
    freed slot and starving the head."""
    clock = FakeClock()
    fs = FairScheduler(clock=clock.now)
    fs.register_job("j", tenant="t", quota={"CPU": 2}, conn_id=1)
    s1 = _spec("t", {"CPU": 1})
    s2 = _spec("t", {"CPU": 1})
    big = _spec("t", {"CPU": 2})
    small = _spec("t", {"CPU": 1})
    assert fs.admit(s1) and fs.admit(s2)
    assert not fs.admit(big)     # over quota: parked
    assert not fs.admit(small)   # would fit a freed slot, but FIFO parks it
    fs.release_admission(s1.task_id)
    assert fs.pop_admissible() == []  # head needs 2 CPU; only 1 free
    fs.release_admission(s2.task_id)
    # strict queue order: big admits first and consumes the quota;
    # small stays parked until big finishes
    assert fs.pop_admissible() == [big]
    fs.release_admission(big.task_id)
    assert fs.pop_admissible() == [small]


# ------------------------------------------------------------- hub E2E


@pytest.fixture
def shutdown_ray():
    yield
    ray_tpu.shutdown()


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def test_blocked_class_does_not_stall_other_classes(shutdown_ray):
    """Satellite regression: a scheduling class whose head task is
    unplaceable (999 chips on a chipless cluster) must not prevent
    same-priority tasks in other classes from dispatching in the same
    scheduler pass."""
    ray_tpu.init(num_cpus=2, num_tpus=0, max_workers=2,
                 ignore_reinit_error=True)

    @ray_tpu.remote(num_tpus=999, num_cpus=0)
    def impossible():
        return "never"

    @ray_tpu.remote(num_cpus=0)
    def light(i):
        return i

    blocked = impossible.remote()
    out = ray_tpu.get([light.remote(i) for i in range(8)], timeout=60)
    assert out == list(range(8))
    ray_tpu.cancel(blocked)


def test_quota_parks_pending_quota_then_completes(shutdown_ray):
    ray_tpu.init(
        num_cpus=4, max_workers=4, ignore_reinit_error=True,
        job_config=JobConfig(tenant="capped", quota={"CPU": 1}),
    )

    @ray_tpu.remote(num_cpus=1)
    def step(i):
        time.sleep(0.2)
        return i

    refs = [step.remote(i) for i in range(4)]
    # with a 1-CPU quota on a 4-CPU cluster, some tasks must park
    deadline = time.monotonic() + 30
    saw_parked = False
    while time.monotonic() < deadline and not saw_parked:
        tenants = {t["tenant"]: t for t in state_api.list_tenants()}
        saw_parked = tenants.get("capped", {}).get("pending_quota", 0) > 0
        time.sleep(0.05)
    assert saw_parked, "no task ever parked as pending_quota"
    # parked demand is flagged so the autoscaler ignores it
    parked_demand = [
        d for d in _client().list_state("demand") if d.get("pending_quota")
    ]
    assert parked_demand and all(
        d["shape"] == {"CPU": 1.0} for d in parked_demand
    )
    # quota is a throttle, not a wall: everything still completes
    assert ray_tpu.get(refs, timeout=60) == list(range(4))
    tenants = {t["tenant"]: t for t in state_api.list_tenants()}
    assert tenants["capped"]["pending_quota"] == 0


def test_nested_submits_inherit_job_identity(shutdown_ray):
    """Quota must not be escapable by fanning out subtasks: a task
    submitted from INSIDE a worker inherits the driver's tenant, so
    nested work is admitted against the same quota and accounted to
    the same tenant."""
    ray_tpu.init(
        num_cpus=4, max_workers=4, ignore_reinit_error=True,
        job_config=JobConfig(
            tenant="nested", quota={"CPU": 2}, job_id="job-nested"
        ),
    )

    @ray_tpu.remote(num_cpus=1)
    def inner(i):
        time.sleep(0.1)
        return i

    @ray_tpu.remote(num_cpus=1)
    def outer(n):
        return ray_tpu.get([inner.remote(i) for i in range(n)])

    assert ray_tpu.get(outer.remote(4), timeout=60) == list(range(4))
    jobs = {j["job_id"]: j for j in state_api.list_jobs()}
    # 1 outer + 4 nested inner submits all accounted to the job
    assert jobs["job-nested"]["submitted"] == 5
    # with outer holding 1 CPU of the 2-CPU quota, inner tasks were
    # throttled through admission (at most 1 concurrent): some parked
    tenants = {t["tenant"]: t for t in state_api.list_tenants()}
    assert tenants["nested"]["pending_quota"] == 0  # all drained


def test_infeasible_submit_fails_instead_of_hanging(shutdown_ray):
    ray_tpu.init(
        num_cpus=4, max_workers=2, ignore_reinit_error=True,
        job_config=JobConfig(tenant="tiny", quota={"CPU": 1}),
    )

    @ray_tpu.remote(num_cpus=2)
    def too_big():
        return 1

    with pytest.raises(Exception, match="never be admitted"):
        ray_tpu.get(too_big.remote(), timeout=30)


def test_killing_quota_parked_actor_unparks_it(shutdown_ray):
    ray_tpu.init(
        num_cpus=2, max_workers=2, ignore_reinit_error=True,
        job_config=JobConfig(tenant="capped", quota={"CPU": 1}),
    )

    @ray_tpu.remote(num_cpus=1)
    def hold():
        time.sleep(1.0)
        return 1

    @ray_tpu.remote(num_cpus=1)
    class Parked:
        def ping(self):
            return "pong"

    blocker = hold.remote()
    # quota is fully admitted by the task: the creation must park
    actor = Parked.remote()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        tenants = {t["tenant"]: t for t in state_api.list_tenants()}
        if tenants.get("capped", {}).get("pending_quota", 0) > 0:
            break
        time.sleep(0.05)
    assert tenants["capped"]["pending_quota"] == 1
    ray_tpu.kill(actor)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        tenants = {t["tenant"]: t for t in state_api.list_tenants()}
        if tenants["capped"]["pending_quota"] == 0:
            break
        time.sleep(0.05)
    assert tenants["capped"]["pending_quota"] == 0, (
        "killed parked actor creation must leave the pending_quota queue"
    )
    assert ray_tpu.get(blocker, timeout=30) == 1


def test_two_tenant_dispatch_interleaves(shutdown_ray):
    """One worker, tenant A floods the queue before tenant B: fair-share
    ordering must interleave completions instead of draining A first."""
    ray_tpu.init(num_cpus=1, max_workers=1, ignore_reinit_error=True)
    cl = _client()
    cl.register_job("job-a", tenant="ta")
    cl.register_job("job-b", tenant="tb")

    @ray_tpu.remote(num_cpus=1)
    def work_a(i):
        time.sleep(0.05)
        return i

    @ray_tpu.remote(num_cpus=1)
    def work_b(i):
        time.sleep(0.05)
        return i

    # warm the single worker so spawn latency doesn't skew the order
    ray_tpu.get(work_a.options(tenant="ta").remote(-1))
    refs_a = [work_a.options(tenant="ta").remote(i) for i in range(8)]
    refs_b = [work_b.options(tenant="tb").remote(i) for i in range(8)]
    ray_tpu.get(refs_a + refs_b, timeout=120)
    events = [
        e for e in state_api.list_tasks()
        if e.get("state") == "FINISHED" and e.get("t_finished")
        and e.get("name", "").startswith("work_")
    ]
    events.sort(key=lambda e: e["t_finished"])
    first_half = [e["name"].split(":")[0] for e in events[:8]]
    # FIFO would put all 8 work_a first; fair share interleaves
    assert first_half.count("work_b") >= 3, first_half


def test_priority_jumps_the_queue(shutdown_ray):
    ray_tpu.init(num_cpus=1, max_workers=1, ignore_reinit_error=True)

    @ray_tpu.remote(num_cpus=1)
    def stamp(tag):
        time.sleep(0.05)
        return (tag, time.monotonic())

    ray_tpu.get(stamp.remote("warm"))  # one live worker, now idle
    blocker = stamp.remote("blocker")
    low = [stamp.options(priority=0).remote(f"low{i}") for i in range(3)]
    high = stamp.options(priority=7).remote("high")
    results = dict(
        t for t in ray_tpu.get(low + [high, blocker], timeout=60)
        if t[0] != "blocker"
    )
    assert results["high"] < min(v for k, v in results.items()
                                 if k.startswith("low")), results


def test_slice_preemption_end_to_end(shutdown_ray, monkeypatch):
    """Acceptance: a priority-10 SLICE reservation preempts a
    priority-0 gang (whole gang, paired preemption/task_retry events),
    and the preempted gang requeues and completes after the
    high-priority job finishes."""
    monkeypatch.setenv("TPU_TOPOLOGY", "1x8")
    ray_tpu.init(num_cpus=8, num_tpus=8, max_workers=8,
                 ignore_reinit_error=True)

    @ray_tpu.remote(num_tpus=2, num_cpus=0, max_retries=0)
    def gang_task(i):
        time.sleep(3)
        return f"low-{i}"

    pg_low = placement_group(
        [{"TPU": 2}] * 4, strategy="SLICE", priority=0, tenant="teamA"
    )
    assert pg_low.wait(15)
    victims = [
        gang_task.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg_low, i)
        ).remote(i)
        for i in range(4)
    ]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        running = [
            t for t in state_api.list_tasks() if t.get("state") == "RUNNING"
        ]
        if len(running) >= 4:
            break
        time.sleep(0.2)
    assert len(running) >= 4, "victim gang never fully started"

    pg_high = placement_group(
        [{"TPU": 8}], strategy="SLICE", priority=10, tenant="teamB"
    )
    assert pg_high.wait(30), "priority-10 SLICE failed to preempt"

    events = state_api.list_events()
    pre = [e for e in events if e["kind"] == "preemption"]
    assert pre, "no preemption event recorded"
    assert pre[0]["by_priority"] == 10 and pre[0]["priority"] == 0
    retried = [
        e for e in events
        if e["kind"] == "task_retry" and e.get("reason") == "preempted"
    ]
    assert len(retried) == 4, "whole gang must requeue (never partial)"

    @ray_tpu.remote(num_tpus=8, num_cpus=0)
    def high_job():
        return "high done"

    assert ray_tpu.get(
        high_job.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg_high, 0)
        ).remote(),
        timeout=60,
    ) == "high done"
    remove_placement_group(pg_high)

    # the victim gang re-reserves its slice and completes successfully
    assert sorted(ray_tpu.get(victims, timeout=120)) == [
        f"low-{i}" for i in range(4)
    ]
    metrics = {
        m["name"]: m for m in _client().list_state("metrics")
    }
    assert metrics["ray_tpu_sched_preemptions_total"]["value"] >= 1


def test_preempted_actor_restarts_via_actor_restart_path(
    shutdown_ray, monkeypatch
):
    monkeypatch.setenv("TPU_TOPOLOGY", "1x4")
    ray_tpu.init(num_cpus=4, num_tpus=4, max_workers=4,
                 ignore_reinit_error=True)

    @ray_tpu.remote(num_tpus=4, num_cpus=0, max_restarts=0)
    class GangMember:
        def ping(self):
            return "pong"

    pg_low = placement_group([{"TPU": 4}], strategy="SLICE", priority=0)
    assert pg_low.wait(15)
    actor = GangMember.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg_low, 0)
    ).remote()
    assert ray_tpu.get(actor.ping.remote(), timeout=60) == "pong"

    pg_high = placement_group([{"TPU": 4}], strategy="SLICE", priority=10)
    assert pg_high.wait(30)
    events = state_api.list_events()
    assert any(e["kind"] == "preemption" for e in events)
    # preemption must not burn the restart budget: max_restarts=0 still
    # restarts through the existing actor_restart path
    assert any(e["kind"] == "actor_restart" for e in events)
    remove_placement_group(pg_high)
    assert ray_tpu.get(actor.ping.remote(), timeout=120) == "pong"


def test_jobs_cli_and_dashboard_tables(shutdown_ray, capsys, monkeypatch):
    ctx = ray_tpu.init(
        num_cpus=2, max_workers=2, ignore_reinit_error=True,
        job_config=JobConfig(
            tenant="cliteam", priority=3, quota={"CPU": 2}, job_id="job-cli"
        ),
    )

    @ray_tpu.remote(num_cpus=1)
    def noop():
        return 1

    ray_tpu.get(noop.remote())
    jobs = state_api.list_jobs()
    assert jobs and jobs[0]["job_id"] == "job-cli"
    assert jobs[0]["tenant"] == "cliteam" and jobs[0]["priority"] == 3
    assert jobs[0]["dispatched"] >= 1

    from ray_tpu.scripts import main as cli_main

    monkeypatch.setenv("RAY_TPU_ADDRESS", ctx.address_info["address"])
    cli_main(["jobs", "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    tenants = {t["tenant"]: t for t in doc["tenants"]}
    assert tenants["cliteam"]["quota"] == {"CPU": 2.0}
    assert any(j["job_id"] == "job-cli" for j in doc["jobs"])
    # table mode renders too
    cli_main(["jobs"])
    out = capsys.readouterr().out
    assert "cliteam" in out and "job-cli" in out
