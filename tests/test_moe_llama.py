"""MoE Llama model family (models/moe_llama.py): routed-FFN transformer
with expert-parallel shardings, trained and sharded on the virtual
8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import MOE_TINY, moe_llama


@pytest.fixture(scope="module")
def params():
    return moe_llama.init_params(jax.random.PRNGKey(0), MOE_TINY)


def test_forward_shapes_and_finiteness(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, MOE_TINY.vocab_size)
    logits, aux = jax.jit(
        lambda p, t: moe_llama.forward(p, t, MOE_TINY)
    )(params, tokens)
    assert logits.shape == (2, 16, MOE_TINY.vocab_size)
    assert jnp.isfinite(logits).all()
    assert jnp.isfinite(aux) and aux > 0  # load-balance loss is positive


def test_param_counts():
    total = moe_llama.param_count(MOE_TINY)
    active = moe_llama.active_param_count(MOE_TINY)
    leaves = jax.tree.leaves(moe_llama.init_params(jax.random.PRNGKey(0), MOE_TINY))
    assert total == sum(int(np.prod(l.shape)) for l in leaves)
    # top-2 of 4 experts: active params strictly fewer than total
    assert active < total


def test_training_reduces_loss(params):
    import optax

    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 33), 0, MOE_TINY.vocab_size)
    batch = {"tokens": tokens}
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(
            lambda p_: moe_llama.loss_fn(p_, batch, MOE_TINY)
        )(p)
        updates, s = opt.update(grads, s)
        return optax.apply_updates(p, updates), s, loss

    p = params
    first = None
    for _ in range(12):
        p, opt_state, loss = step(p, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first - 0.3, (first, float(loss))


def test_expert_parallel_sharded_forward(params):
    """Experts sharded over a real `expert` mesh axis; GSPMD inserts the
    dispatch all-to-all. Output must match the unsharded forward."""
    import dataclasses

    # float32 activations: sharding must be value-preserving, and fp32
    # keeps GSPMD's different reduction orders within tight tolerance
    # (bf16 reordering noise would swamp the comparison)
    cfg = dataclasses.replace(MOE_TINY, dtype=jnp.float32)
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("fsdp", "expert"))
    # MOE_TINY has 4 experts -> 1 per expert-mesh column
    specs = moe_llama.param_specs(cfg)

    def shard_spec(spec):
        # drop axes this 2-axis test mesh doesn't have
        return P(*(
            ax if ax in ("fsdp", "expert") else None
            for ax in (tuple(spec) if spec else ())
        ))

    sharded = jax.tree.map(
        lambda arr, spec: jax.device_put(
            arr, NamedSharding(mesh, shard_spec(spec))
        ),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P) or not isinstance(x, dict),
    )
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size)
    ref_logits, ref_aux = jax.jit(
        lambda p, t: moe_llama.forward(p, t, cfg)
    )(params, tokens)
    with mesh:
        out_logits, out_aux = jax.jit(
            lambda p, t: moe_llama.forward(p, t, cfg)
        )(sharded, tokens)
    np.testing.assert_allclose(
        np.asarray(out_logits), np.asarray(ref_logits), atol=2e-4
    )
    np.testing.assert_allclose(float(out_aux), float(ref_aux), rtol=1e-4)


def test_pad_tokens_excluded_from_moe():
    """Masked tokens get no expert (zero output) and are excluded from
    the load-balance statistics."""
    from ray_tpu.ops import MoEConfig, init_moe_params, moe_ffn

    cfg = MoEConfig(d_model=16, d_ff=32, n_experts=4, k=2)
    p = init_moe_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16), jnp.float32)
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.float32)
    out, aux = moe_ffn(p, x, cfg, mask=mask)
    np.testing.assert_allclose(np.asarray(out[0, 4:]), 0.0)
    # balance stats are pre-drop means over REAL tokens: identical to
    # running the unpadded prefix alone
    _, aux_ref = moe_ffn(p, x[:, :4], cfg)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)
