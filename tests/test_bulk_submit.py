"""Vectorized task submission (RemoteFunction.map / client.submit_many).

Tier-1 coverage for the bulk wire path:
  - map() semantics: tuple splats, single args, empty input, result
    order, num_returns > 1, streaming rejection;
  - FIFO interleaving: a bulk batch and surrounding singles on the same
    connection execute in submission order (per-conn FIFO holds across
    the SUBMIT_TASKS frame boundary);
  - registration cache: _ensure_exported ships the function blob once
    per client epoch and re-exports after an epoch bump (reconnect);
  - per-task isolation inside one frame, and pipelined-follower requeue
    when a worker crashes mid-batch;
  - sharded parity: the 4-shard control plane admits a bulk frame
    identically to the single reactor;
  - trace stitching: ONE client.submit span fans out to N hub.admit
    children.
"""

import os
import time

import pytest

import ray_tpu


def test_map_basic_shapes(ray_start_4_cpus):
    @ray_tpu.remote
    def add(a, b=0):
        return a + b

    # tuple items splat into positionals; non-tuples are single args
    refs = add.map([(1, 2), (3, 4), 5, (6,)])
    assert ray_tpu.get(refs, timeout=60) == [3, 7, 5, 6]

    # a tuple ARG must be wrapped once more — ((x, y),) ships the tuple
    @ray_tpu.remote
    def first(pair):
        return pair[0]

    assert ray_tpu.get(first.map([((9, 8),)]), timeout=60) == [9]
    assert add.map([]) == []


def test_map_result_order_is_submission_order(ray_start_4_cpus):
    @ray_tpu.remote
    def ident(i):
        return i

    out = ray_tpu.get(ident.map(list(range(100))), timeout=60)
    assert out == list(range(100))


def test_map_num_returns(ray_start_4_cpus):
    @ray_tpu.remote(num_returns=2)
    def split(i):
        return i, -i

    rows = split.map([1, 2, 3])
    assert all(len(r) == 2 for r in rows)
    assert [ray_tpu.get(list(r), timeout=60) for r in rows] == [
        [1, -1], [2, -2], [3, -3]]


def test_map_rejects_streaming(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    with pytest.raises(ValueError):
        gen.map([3, 4])


def test_bulk_interleaves_fifo_with_singles(ray_start_4_cpus):
    """A single, a bulk batch, and another single submitted on one
    connection must be admitted in that order: the hub appends to the
    same runnable queue whether tasks arrive framed singly or in one
    SUBMIT_TASKS frame. Each task claims the whole node (num_cpus=4),
    so execution is strictly serial and completion timestamps reveal
    admission order."""
    @ray_tpu.remote(num_cpus=4)
    def stamp(_tag):
        return time.monotonic()

    head = stamp.remote("head")
    bulk = stamp.map([(f"b{i}",) for i in range(6)])
    tail = stamp.remote("tail")
    times = ray_tpu.get([head, *bulk, tail], timeout=90)
    assert times == sorted(times), "bulk frame broke per-conn FIFO order"


def test_function_exported_once_per_epoch(ray_start_regular, monkeypatch):
    """A map() wave ships the function blob to the hub exactly once;
    the second wave is a pure epoch-compare cache hit."""
    from ray_tpu._private import worker

    client = worker.get_client()
    calls = []
    orig = client.register_function

    def spy(fn_id, blob, *a, **k):
        calls.append(fn_id)
        return orig(fn_id, blob, *a, **k)

    monkeypatch.setattr(client, "register_function", spy)

    @ray_tpu.remote
    def f(i):
        return i + 1

    assert ray_tpu.get(f.map(list(range(10))), timeout=60) == list(range(1, 11))
    assert f._export_epoch == client.client_epoch
    assert len([c for c in calls if c == f._fn_id]) == 1
    assert ray_tpu.get(f.map(list(range(5))), timeout=60) == list(range(1, 6))
    assert len([c for c in calls if c == f._fn_id]) == 1


def test_export_cache_invalidated_on_epoch_bump(ray_start_regular, monkeypatch):
    """A reconnect builds a new CoreClient with a fresh epoch; the
    registration memo keys on that epoch, so a bump must force a
    re-export on the next map()."""
    from ray_tpu._private import worker

    @ray_tpu.remote
    def g(i):
        return i * 3

    assert ray_tpu.get(g.map([1, 2]), timeout=60) == [3, 6]
    client = worker.get_client()
    calls = []
    orig = client.register_function

    def spy(fn_id, blob, *a, **k):
        calls.append(fn_id)
        return orig(fn_id, blob, *a, **k)

    monkeypatch.setattr(client, "register_function", spy)
    # simulate what a reconnect does to the memo: the epoch moves on
    client.client_epoch += 1
    assert ray_tpu.get(g.map([4, 5]), timeout=60) == [12, 15]
    assert g._fn_id in calls, "epoch bump did not force a re-export"
    assert g._export_epoch == client.client_epoch


def test_bulk_with_failing_members(ray_start_4_cpus):
    """Per-task isolation inside one frame: a raising member fails its
    OWN ObjectRef only."""
    @ray_tpu.remote(max_retries=0)
    def maybe(i):
        if i % 3 == 0:
            raise ValueError(f"boom {i}")
        return i

    refs = maybe.map(list(range(9)))
    for i, r in enumerate(refs):
        if i % 3 == 0:
            with pytest.raises(Exception):
                ray_tpu.get(r, timeout=30)
        else:
            assert ray_tpu.get(r, timeout=30) == i


def test_pipelined_bulk_survives_worker_crash(ray_start_4_cpus, tmp_path):
    """Deep bulk fan-out engages dispatch pipelining (followers queue
    behind busy workers). A worker crash mid-batch must requeue its
    followers without burning their retry budget: every task still
    completes with the right value."""
    marker = str(tmp_path / "crashed_once")

    @ray_tpu.remote(max_retries=2)
    def work(i, marker):
        if i == 17 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)  # hard crash, not an exception
        return i

    out = ray_tpu.get(work.map([(i, marker) for i in range(64)]), timeout=120)
    assert out == list(range(64))


def test_actor_pool_map_rides_bulk_window(ray_start_4_cpus):
    from ray_tpu.util.actor_pool import ActorPool

    @ray_tpu.remote
    class Doubler:
        def go(self, x):
            return 2 * x

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    got = list(pool.map(lambda a, v: a.go.remote(v), range(20)))
    assert got == [2 * i for i in range(20)]


def test_sharded_hub_bulk_parity(monkeypatch):
    """The 4-shard control plane must admit a SUBMIT_TASKS frame
    identically to the single reactor: same results, same order."""
    monkeypatch.setenv("RAY_TPU_HUB_SHARDS", "4")
    ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    try:
        from ray_tpu._private import worker

        assert worker._hub is not None and worker._hub.n_shards == 4

        @ray_tpu.remote
        def sq(i):
            return i * i

        assert ray_tpu.get(sq.map(list(range(50))), timeout=90) == [
            i * i for i in range(50)
        ]
    finally:
        ray_tpu.shutdown()


def test_bulk_trace_one_submit_many_admits(monkeypatch):
    """ONE client.submit span per map() call; the hub fans it out to N
    hub.admit children parented under it."""
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    try:
        from ray_tpu._private import worker

        client = worker.get_client()

        @ray_tpu.remote
        def t(i):
            return i

        n = 8
        assert ray_tpu.get(t.map(list(range(n))), timeout=60) == list(range(n))

        deadline = time.monotonic() + 15.0
        good_spans = None
        while time.monotonic() < deadline and good_spans is None:
            for row in client.list_state("traces"):
                spans = client.list_state("traces", trace_id=row["trace_id"])
                submits = [s for s in spans if s.get("name") == "client.submit"]
                admits = [s for s in spans if s.get("name") == "hub.admit"]
                execs = [s for s in spans if s.get("name") == "worker.execute"]
                # wait for the execute spans too: the analyzer below
                # needs the full stage picture, not just the admission
                if len(submits) == 1 and len(admits) == n and len(execs) >= n:
                    root = submits[0]["span_id"]
                    if all(a.get("parent_id") == root for a in admits):
                        good_spans = spans
                        break
            if good_spans is None:
                time.sleep(0.1)
        assert good_spans, "no trace with 1 client.submit + N hub.admit children"

        # the perf claim behind map(): the client-side submit stage is
        # no longer where a bulk fan-out's time goes (PR-8 analyzer;
        # one shared submit span over N tasks makes its share ~1/N of
        # the per-call path even before the wire savings)
        from ray_tpu.util.tracing import analyze_trace

        analysis = analyze_trace(good_spans)
        assert analysis["dominant_stage"] != "submit", analysis["stages"]
    finally:
        ray_tpu.shutdown()
