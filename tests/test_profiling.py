"""Sampling profiler + remote stack dumps (profiling.py, util/profiler).

Reference surfaces: `ray stack` and the dashboard's py-spy profiling
endpoints — here re-done in-process. Covers the frame classifier, the
sampler lifecycle, hub aggregation with per-task attribution, the
zero-cost-when-off guard the tier-1 suite enforces, and the CLI verbs.
"""

import os
import threading
import time

import pytest

from ray_tpu._private import profiling


def _wait_for(cond, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return False


# ------------------------------------------------------------- classifier
def test_classify_stage_buckets():
    pkg = profiling._PKG_DIR
    assert profiling.classify_stage(
        [(f"{pkg}/_private/serialization.py", "dumps_frame")]
    ) == "frame-encode"
    assert profiling.classify_stage(
        [("/usr/lib/python3.10/pickle.py", "dump")]
    ) == "serialize"
    assert profiling.classify_stage(
        [("/usr/lib/python3.10/selectors.py", "select")]
    ) == "reactor-poll"
    assert profiling.classify_stage(
        [("/usr/lib/python3.10/socket.py", "recv_into")]
    ) == "recv/send"
    assert profiling.classify_stage(
        [("/usr/lib/python3.10/threading.py", "wait"),
         ("/home/user/app.py", "work")]
    ) == "lock-wait"
    assert profiling.classify_stage(
        [("/home/user/train.py", "step")]
    ) == "user-code"
    # REPL/exec-defined user functions keep their synthetic filename
    assert profiling.classify_stage([("<stdin>", "burn")]) == "user-code"
    # runtime-internal frames only -> runtime
    assert profiling.classify_stage(
        [(f"{pkg}/_private/hub.py", "_dispatch"),
         ("<frozen importlib._bootstrap>", "_find_and_load")]
    ) == "runtime"
    assert profiling.classify_stage([]) == "runtime"


def test_classify_stage_idle_vs_lock_wait():
    pkg = profiling._PKG_DIR
    # executor parked between tasks: queue.get directly under the
    # worker dispatch loop is idle, not a lock stall
    idle_stack = [
        ("/usr/lib/python3.10/queue.py", "get"),
        (f"{pkg}/_private/worker_process.py", "main"),
    ]
    assert profiling.classify_stage(idle_stack) == "idle"
    # the same queue.get under user code IS a wait worth surfacing
    user_wait = [
        ("/usr/lib/python3.10/queue.py", "get"),
        ("/home/user/pipeline.py", "consume"),
    ]
    assert profiling.classify_stage(user_wait) == "lock-wait"


def test_classify_thread_domains():
    assert profiling.classify_thread("MainThread") == "main"
    assert profiling.classify_thread("ray-tpu-hub") == "reactor"
    assert profiling.classify_thread("ray-tpu-hub-shard-2") == "shard"
    assert profiling.classify_thread("core-client-reader") == "reader"
    assert profiling.classify_thread("core-client-flusher") == "flusher"
    assert profiling.classify_thread("ray-tpu-profile-sampler") == "profiler"
    assert profiling.classify_thread("my-own-thread") == "my-own-thread"


def test_collapse_is_root_to_leaf():
    pairs = [("/a/leaf.py", "inner"), ("/a/mid.py", "call"),
             ("/a/root.py", "main")]  # leaf-first, as sampled
    assert profiling._collapse(pairs) == "root:main;mid:call;leaf:inner"


# ---------------------------------------------------------------- sampler
def test_maybe_start_off_creates_nothing(monkeypatch):
    monkeypatch.delenv("RAY_TPU_PROFILE_HZ", raising=False)
    before = set(threading.enumerate())
    assert profiling.maybe_start("test", lambda b: None) is None
    assert profiling._SAMPLER is None
    assert not profiling._ACTIVE
    assert set(threading.enumerate()) == before


def test_sampler_folds_and_flushes():
    batches = []
    try:
        s = profiling.maybe_start(
            "unit", batches.append, hz=200.0, flush_period=0.2
        )
        assert s is not None
        assert profiling._ACTIVE
        profiling.set_task(b"\xab\xcd")  # this thread shows up attributed
        spin_until = time.monotonic() + 0.1
        while time.monotonic() < spin_until:
            pass  # give the sampler something on-CPU to see
        assert _wait_for(lambda: batches, timeout=10)
        batch = batches[0]
        assert batch["kind"] == "unit"
        assert batch["pid"] == os.getpid()
        assert 0.0 <= batch["overhead"] < 1.0
        assert batch["samples"]
        key, n = next(iter(batch["samples"].items()))
        domain, stage, task, stack = key
        assert stage in profiling.STAGES
        assert n >= 1
        # this test thread's samples carry its registered task id
        assert any(k[2] == "abcd" for k in batch["samples"])
        # the sampler never samples itself
        assert all(k[0] != "profiler" for k in batch["samples"])
    finally:
        profiling.stop()
    assert profiling._SAMPLER is None
    assert not profiling._ACTIVE
    assert profiling._TASK_REGISTER == {}


def test_sampler_auto_clamps_past_budget():
    try:
        s = profiling.maybe_start(
            "clamp", lambda b: None, hz=128.0, budget=1e-9,
            flush_period=0.2,
        )
        assert s is not None
        # any nonzero sampling cost exceeds the absurd budget: the rate
        # halves every window down to the 1 Hz floor
        assert _wait_for(lambda: s.clamped, timeout=10)
        assert s.hz < 128.0
        assert s.hz >= 1.0
    finally:
        profiling.stop()


def test_dump_threads_sees_all_threads():
    evt = threading.Event()
    t = threading.Thread(target=evt.wait, name="dumpee", daemon=True)
    t.start()
    try:
        dump = profiling.dump_threads()
        by_name = {d["thread"]: d for d in dump}
        assert "MainThread" in by_name
        assert "dumpee" in by_name
        frames = "\n".join(by_name["dumpee"]["frames"])
        assert "evt.wait" in frames or "threading" in frames
        assert by_name["dumpee"]["daemon"] is True
    finally:
        evt.set()


# ----------------------------------------------------- report-side helpers
def _row(pid=1, kind="worker", thread="main", stage="user-code",
         task_id="", task_name="", stack="a:b;c:d", samples=1):
    return {"pid": pid, "kind": kind, "thread": thread, "stage": stage,
            "task_id": task_id, "task_name": task_name, "stack": stack,
            "samples": samples}


def test_profiler_diff_fold_top():
    from ray_tpu.util import profiler as prof

    before = [_row(samples=5), _row(stage="idle", samples=3)]
    after = [
        _row(samples=9),                      # 4 new
        _row(stage="idle", samples=3),        # unchanged: dropped
        _row(stage="serialize", samples=2),   # new key
        {"proc": True, "pid": 1, "kind": "worker", "hz": 50.0,
         "overhead": 0.01, "drops": 0},
    ]
    d = prof.diff(before, after)
    data = [r for r in d if not r.get("proc")]
    assert {(r["stage"], r["samples"]) for r in data} == {
        ("user-code", 4), ("serialize", 2)
    }
    assert prof.overhead(d) == [after[-1]]

    lines = prof.fold_lines(
        [_row(task_id="deadbeef" * 2, task_name="burn", samples=7)]
    )
    assert lines == [
        "worker:1;main;user-code;task:deadbeef (burn);a:b;c:d 7"
    ]

    tops = prof.top(
        [_row(stage="user-code", samples=6), _row(stage="idle", samples=2)],
        by="stage",
    )
    assert tops[0] == {"stage": "user-code", "samples": 6, "share": 0.75}


# -------------------------------------------------------- live-cluster: off
def test_profiler_off_is_truly_zero_cost(ray_start_regular):
    """Tier-1 guard: with RAY_TPU_PROFILE_HZ at its default 0, no
    sampler thread exists anywhere, no PROFILE_BATCH ever reaches the
    hub, and the profile state table is empty."""
    import ray_tpu
    from ray_tpu.util import profiler as prof
    from ray_tpu.util.state import list_profile

    assert not profiling._ACTIVE
    assert profiling._SAMPLER is None
    assert not any(
        "profile-sampler" in t.name for t in threading.enumerate()
    )

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get([f.remote() for _ in range(4)]) == [1] * 4
    assert list_profile() == []  # no batches arrived, no procs reported

    # a worker's threads, dumped live: no sampler there either
    from ray_tpu.util.state import list_workers

    assert _wait_for(
        lambda: any(w.get("pid") for w in list_workers()), timeout=15
    )
    wid = next(w["worker_id"] for w in list_workers() if w.get("pid"))
    dump = prof.stack(wid)
    assert dump.get("threads") and not dump.get("error")
    assert not any(
        "profile-sampler" in t["thread"] for t in dump["threads"]
    )


# --------------------------------------------------------- live-cluster: on
@pytest.fixture
def profiled_cluster(monkeypatch):
    import ray_tpu

    monkeypatch.setenv("RAY_TPU_PROFILE_HZ", "50")
    monkeypatch.setenv("RAY_TPU_PROFILE_FLUSH_PERIOD_S", "0.3")
    ctx = ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()
    profiling.stop()  # belt and braces: never leak a sampler into the
    # next test even if shutdown's path changes


def test_profiler_attributes_tasks_and_stages(profiled_cluster):
    """The acceptance path: a task burst under an active sampler yields
    samples attributed to a named task id AND a named runtime stage."""
    import ray_tpu
    from ray_tpu.util.state import list_profile

    @ray_tpu.remote
    def burn(sec):
        t0 = time.time()
        x = 0
        while time.time() - t0 < sec:
            x += sum(i * i for i in range(2000))
        return x

    refs = [burn.remote(0.4) for _ in range(4)]
    ray_tpu.get(refs)

    def attributed():
        rows = [r for r in list_profile() if not r.get("proc")]
        return [
            r for r in rows
            if r["task_id"] and r["task_name"].startswith("burn")
            and r["stage"] in profiling.STAGES
        ]

    assert _wait_for(lambda: attributed(), timeout=20)
    rows = list_profile()
    samples = [r for r in rows if not r.get("proc")]
    procs = [r for r in rows if r.get("proc")]
    # every sampled process reported its meta row: driver + workers
    assert any(p["kind"] == "driver" or p["kind"] == "hub" for p in procs)
    assert any(p["kind"] == "worker" for p in procs)
    assert all(p["hz"] > 0 for p in procs)
    # stacks are folded root->leaf flamegraph strings
    assert any(";" in r["stack"] for r in samples)
    # the self-overhead gauge is live in the metric registry
    from ray_tpu.util.metrics import snapshot

    assert any(
        m["name"] == "ray_tpu_profiler_overhead_ratio" for m in snapshot()
    )


def test_profile_window_and_cli(profiled_cluster, tmp_path, capsys):
    import ray_tpu
    from ray_tpu import scripts
    from ray_tpu.util import profiler as prof

    @ray_tpu.remote
    def spin(sec):
        t0 = time.time()
        while time.time() - t0 < sec:
            sum(i * i for i in range(1000))
        return 0

    refs = [spin.remote(1.5) for _ in range(2)]
    rows = prof.profile(1.2)  # windows the burst
    ray_tpu.get(refs)
    assert [r for r in rows if not r.get("proc")]

    out = tmp_path / "folded.txt"
    addr = profiled_cluster.address_info["address"]
    scripts.main([
        "profile", "--duration", "1.0", "--fold", str(out),
        "--top", "stage", "--address", addr,
    ])
    text = out.read_text()
    assert text.strip()
    # every folded line is "semi;colon;stack count"
    for line in text.strip().splitlines():
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit()
    printed = capsys.readouterr().out
    assert "samples by stage" in printed


def test_stack_cli_and_unknown_target(ray_start_regular, capsys):
    from ray_tpu import scripts
    from ray_tpu.util import profiler as prof

    addr = ray_start_regular.address_info["address"]
    scripts.main(["stack", "hub", "--address", addr])
    out = capsys.readouterr().out
    assert "MainThread" in out and "pid=" in out

    reply = prof.stack("definitely-not-a-worker")
    assert reply.get("error")
    assert reply.get("threads") == []

    with pytest.raises(SystemExit):
        scripts.main([
            "stack", "definitely-not-a-worker", "--address", addr,
        ])


# --------------------------------------------------- memory / leak suspects
def test_objects_owner_age_and_leak_suspects(ray_start_regular):
    import ray_tpu
    from ray_tpu.util import state as state_api

    ref = ray_tpu.put(b"x" * 128)
    objs = state_api.list_objects()
    mine = [o for o in objs if o["object_id"] == ref.hex()]
    assert mine, objs
    o = mine[0]
    assert o["owner"] == "driver"
    assert o["owner_alive"] is True
    assert o["age_s"] >= 0.0
    assert o["size"] >= 128

    summary = state_api.summarize_objects()
    assert summary["by_owner"]["driver"]["count"] >= 1
    assert summary["leak_suspects"] == 0

    # a dead owner with no pins IS a suspect; pins or youth exempt it
    fake = [
        dict(o, owner="client-9", owner_alive=False, age_s=300.0, pins=0),
        dict(o, owner="client-9", owner_alive=False, age_s=300.0, pins=2),
        dict(o, owner="client-9", owner_alive=False, age_s=1.0, pins=0),
    ]
    suspects = state_api.leak_suspects(min_age_s=60.0, objects=fake)
    assert suspects == [fake[0]]
    del ref


def test_memory_cli_table_and_leak_flag(ray_start_regular, capsys):
    import ray_tpu
    from ray_tpu import scripts

    ref = ray_tpu.put(b"y" * 64)
    addr = ray_start_regular.address_info["address"]
    scripts.main(["memory", "--address", addr])
    out = capsys.readouterr().out
    assert "OWNER" in out and "AGE_S" in out
    assert ref.hex()[:16] in out
    assert "leak suspect" in out

    scripts.main(["memory", "--leak-suspects", "--address", addr])
    out = capsys.readouterr().out
    # live driver-owned objects are filtered out of the suspect view
    assert ref.hex()[:16] not in out
    del ref
