"""Seeded chaos soak: the whole fault plane against a mixed workload.

The headline artifact of the fault-injection plane (chaos.py): one
seeded RAY_TPU_CHAOS_PLAN throws message drops, delays, duplicates, a
client connection kill, a worker SIGKILL, a worker SIGSTOP (hang, not
death), a node partition (heartbeat + data blackhole -> heartbeat-miss
node death), and mid-stream object-transfer death at a simulated
two-host cluster running tasks, actor calls, puts/gets, and one lineage
reconstruction — then asserts end-state invariants:

  - every submitted task resolves: a correct value, or an explicit
    error (the killed client's ConnectionError; actor calls in flight
    at a worker fault surface ActorDiedError) — never a hang,
  - no wedged get(): the whole workload completes inside the timeout,
  - no leaked registries: parked requests, fetches, waiters, the
    killed client's fairsched job/tenant rows all drain to empty,
  - reproducibility: a second run with the SAME seed produces the
    identical deterministic outcome (task results, put round-trips,
    reconstruction checksum).

Deterministic-schedule discipline per FoundationDB-style simulation
testing (and rpc_chaos.h's env-selected failure injection): the fault
schedule is a pure function of the plan, so a failing seed is a
reproducible bug report.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# drops target retry-safe (replied, idempotent) request types — the
# backoff retransmit layer recovers; delays are safe on any type; dups
# target the idempotent-by-upsert types (put first-write-wins,
# submit_task deduped by task id). conn_kill takes the extra client,
# worker_kill/hang hit busy workers, the partition blackholes node1
# until the heartbeat-miss watchdog declares it dead, and close_after
# kills every direct object transfer mid-stream (relay fallback).
SOAK_PLAN = (
    "seed={seed};"
    "drop:get@0.2;drop:wait@0.2;drop:subscribe_ready@0.2;"
    "drop:fetch_object@0.2;drop:resolve_object@0.3;"
    "delay:task_done@1ms-10ms;delay:submit_task@1ms-5ms@0.3;"
    "dup:put@0.5;dup:submit_task@0.3;"
    "conn_kill:client@1s;worker_kill:1@1.2s;worker_hang:1@2s;"
    "partition:node1@3s-120s;close_after:2"
)

SOAK_ENV = {
    # 8 * 0.25s = a 2s silence threshold: comfortably above the agent's
    # heartbeat jitter on a loaded 1-core box, comfortably below the
    # partition window's length
    "RAY_TPU_NODE_HEARTBEAT_PERIOD_S": "0.25",
    "RAY_TPU_NODE_HEARTBEAT_MISS_THRESHOLD": "8",
    # hung-worker watchdog: recovers the SIGSTOP'd worker's task even
    # where no per-task timeout_s was set
    "RAY_TPU_TASK_TIMEOUT_DEFAULT_S": "2.5",
}


def _run_soak(seed: int) -> dict:
    """One full soak run; returns the deterministic outcome record."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.client import CoreClient

    outcome = {}
    cluster = Cluster(head_num_cpus=2)
    try:
        cluster.add_node(num_cpus=2, resources={"eph": 4.0})
        hub = worker_mod._hub
        driver = worker_mod.get_client()
        assert hub._chaos is not None, "plan env did not reach the hub"

        # ---- reconstruction candidate: produced on doomed node1
        @ray_tpu.remote(resources={"eph": 1.0}, max_retries=2)
        def make():
            return np.arange(60_000, dtype=np.float64)

        recon_ref = make.remote()
        ready, _ = ray_tpu.wait([recon_ref], num_returns=1, timeout=30)
        assert ready, "producer never finished on node1"

        # ---- the conn_kill victim: a second (non-driver) client with
        # a registered fairsched identity, so the kill must prune the
        # job/tenant registries too
        extra = CoreClient(
            hub.addr, driver.session_dir, role="client",
            worker_id="soak-extra",
        )
        extra.register_job("soak-extra", tenant="chaos-victim")
        assert any(
            j["job_id"] == "soak-extra" for j in driver.list_state("jobs")
        )

        # ---- mixed workload riding through the fault window
        @ray_tpu.remote(max_retries=4)
        def work(i):
            time.sleep(0.05 + (i % 4) * 0.1)
            return i * 7

        @ray_tpu.remote(max_restarts=5)
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        put_refs = [
            ray_tpu.put(np.full(512, i, dtype=np.int64)) for i in range(4)
        ]
        task_refs = [
            work.options(timeout_s=4.0).remote(i) for i in range(24)
        ]
        c = Counter.remote()
        actor_refs = [c.bump.remote() for _ in range(10)]

        # deterministic values: every task retries through worker
        # kill/hang to its correct result
        results = ray_tpu.get(task_refs, timeout=120)
        outcome["task_results"] = results
        outcome["put_sums"] = [
            int(ray_tpu.get(r, timeout=60).sum()) for r in put_refs
        ]
        # actor calls resolve (value or explicit death error) — a
        # worker fault may take the actor mid-call, so values are not
        # part of the deterministic record, resolution is
        actor_out = []
        for r in actor_refs:
            try:
                actor_out.append(int(ray_tpu.get(r, timeout=60)))
            except ray_tpu.exceptions.RayError as err:
                actor_out.append(type(err).__name__)
        assert len(actor_out) == 10

        # ---- the killed client is dead and fully pruned
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and len(hub.client_conns) > 1:
            time.sleep(0.1)
        assert len(hub.client_conns) == 1, "extra client never expelled"
        with pytest.raises((ConnectionError, OSError, TimeoutError)):
            extra.request("cluster_resources", {"available": False},
                          timeout=5)
        assert not any(
            j["job_id"] == "soak-extra" for j in driver.list_state("jobs")
        )

        # ---- partition -> heartbeat-miss -> node death -> reconstruct
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            alive = {
                n["node_id"]: n["alive"] for n in ray_tpu.nodes()
            }
            if alive.get("node1") is False:
                break
            time.sleep(0.2)
        else:
            raise AssertionError("partitioned node1 never declared dead")
        cluster.add_node(num_cpus=2, resources={"eph": 4.0})  # rerun room
        arr = ray_tpu.get(recon_ref, timeout=60)
        outcome["recon_checksum"] = int(arr.sum())

        # ---- every scheduled fault actually fired
        kinds = {e["kind"] for e in driver.list_state("events")}
        for want in ("chaos_conn_kill", "chaos_worker_kill",
                     "chaos_worker_hang", "chaos_partition_drop",
                     "node_heartbeat_miss", "node_down"):
            assert want in kinds, f"fault {want} never fired: {kinds}"

        # ---- end-state invariants: nothing wedged, nothing leaked
        deadline = time.monotonic() + 10
        leak = None
        while time.monotonic() < deadline:
            leak = _leaks(hub)
            if leak is None:
                break
            time.sleep(0.2)
        assert leak is None, f"leaked registry entries: {leak}"
        stuck = [
            t["task_id"] for t in driver.list_state("tasks")
            if t.get("state") not in ("FINISHED", "FAILED")
        ]
        assert not stuck, f"tasks never resolved: {stuck}"
        try:
            extra.close()
        except Exception:
            pass
    finally:
        cluster.shutdown()
    return outcome


def _leaks(hub):
    """None when every transient registry drained, else a description."""
    if hub._inflight_reqs:
        return f"_inflight_reqs: {len(hub._inflight_reqs)}"
    if hub._pending_fetches:
        return f"_pending_fetches: {len(hub._pending_fetches)}"
    if hub.obj_get_waiters:
        return f"obj_get_waiters: {len(hub.obj_get_waiters)}"
    if hub.obj_wait_waiters:
        return f"obj_wait_waiters: {len(hub.obj_wait_waiters)}"
    if hub._reconstruct_waiters:
        return f"_reconstruct_waiters: {len(hub._reconstruct_waiters)}"
    if hub.fairsched.parked_count():
        return f"pending_quota: {hub.fairsched.parked_count()}"
    busy = [
        w.worker_id for w in hub.workers.values() if w.state == "busy"
    ]
    if busy:
        return f"busy workers: {busy}"
    return None


def test_chaos_soak_seeded_and_reproducible(monkeypatch):
    """The full seeded schedule, twice: both runs satisfy every
    invariant and the deterministic outcome records are identical."""
    seed = 1234
    for k, v in SOAK_ENV.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setenv("RAY_TPU_CHAOS_PLAN", SOAK_PLAN.format(seed=seed))
    from ray_tpu._private.client import CoreClient

    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.2)
    first = _run_soak(seed)
    assert first["task_results"] == [i * 7 for i in range(24)]
    assert first["put_sums"] == [512 * i for i in range(4)]
    assert first["recon_checksum"] == sum(range(60_000))
    second = _run_soak(seed)
    assert second == first, (
        f"same seed, different outcome:\n{first}\nvs\n{second}"
    )
