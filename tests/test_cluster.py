"""Multi-node runtime tests on the simulated cluster (Cluster harness —
reference parity: python/ray/cluster_utils.py:135 + tests using
ray_start_cluster). Each node is a real separate agent process with its
own session dir, so scheduling, cross-node objects, and placement all
take the true multi-process paths."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_num_cpus=2)
    c.add_node(num_cpus=2, resources={"nodeA": 4.0})
    c.add_node(num_cpus=2, resources={"nodeB": 4.0})
    yield c
    c.shutdown()


def _my_node():
    import os

    return os.environ.get("RAY_TPU_NODE_ID", "node0")


def test_nodes_registered(cluster):
    nodes = ray_tpu.nodes()
    alive = {n["node_id"] for n in nodes if n["alive"]}
    assert {"node0", "node1", "node2"} <= alive
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 6.0
    assert total["nodeA"] == 4.0


def test_task_targets_custom_resource_node(cluster):
    @ray_tpu.remote(resources={"nodeB": 1.0})
    def where():
        return _my_node()

    assert ray_tpu.get(where.remote()) == "node2"


def test_node_affinity_strategy(cluster):
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray_tpu.remote
    def where():
        return _my_node()

    got = ray_tpu.get(
        where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_id="node1")
        ).remote()
    )
    assert got == "node1"


def test_tasks_spread_across_nodes(cluster):
    import time

    @ray_tpu.remote(num_cpus=1)
    def where(i):
        time.sleep(0.3)
        return _my_node()

    # 6 concurrent 1-CPU tasks > head's 2 CPUs: must spill to other nodes
    got = set(ray_tpu.get([where.remote(i) for i in range(6)]))
    assert len(got) >= 2, got


def test_cross_node_shm_object(cluster):
    @ray_tpu.remote(resources={"nodeA": 1.0})
    def make():
        return np.arange(200_000, dtype=np.float64)  # 1.6MB -> shm segment

    ref = make.remote()
    arr = ray_tpu.get(ref)  # driver is on node0: cross-node fetch
    assert arr.shape == (200_000,)
    assert float(arr[123_456]) == 123_456.0

    @ray_tpu.remote(resources={"nodeB": 1.0})
    def consume(a):
        return float(a.sum())

    # node2 consumes an object produced on node1
    assert ray_tpu.get(consume.remote(ref)) == float(arr.sum())


def test_actor_on_remote_node_roundtrip(cluster):
    @ray_tpu.remote(resources={"nodeA": 1.0})
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, k):
            self.n += k
            return self.n

        def node(self):
            return _my_node()

    c = Counter.remote()
    assert ray_tpu.get(c.node.remote()) == "node1"
    assert ray_tpu.get([c.bump.remote(2), c.bump.remote(3)]) == [2, 5]
    ray_tpu.kill(c)


def test_strict_spread_pg(cluster):
    from ray_tpu.util.placement_group import placement_group
    from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=10)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return _my_node()

    got = ray_tpu.get(
        [
            where.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=i
                )
            ).remote()
            for i in range(3)
        ]
    )
    assert sorted(got) == ["node0", "node1", "node2"], got
    from ray_tpu.util.placement_group import remove_placement_group

    remove_placement_group(pg)


def test_node_death_detected(cluster):
    node = cluster.add_node(num_cpus=1, resources={"dying": 1.0})
    assert any(
        n["node_id"] == node.node_id and n["alive"] for n in ray_tpu.nodes()
    )
    cluster.remove_node(node)
    entry = [n for n in ray_tpu.nodes() if n["node_id"] == node.node_id]
    assert entry and not entry[0]["alive"]
    total = ray_tpu.cluster_resources()
    assert "dying" not in total


def test_lineage_reconstruction_after_node_death(cluster):
    """An object whose only copy died with its node is reconstructed by
    re-running the producing task (reference: object_recovery_manager.h
    re-execution path), transparently inside ray_tpu.get."""
    node = cluster.add_node(num_cpus=2, resources={"ephemeral": 4.0})

    @ray_tpu.remote(resources={"ephemeral": 1.0}, max_retries=2)
    def make():
        return np.arange(250_000, dtype=np.float64)  # shm segment

    ref = make.remote()
    # materialize on the doomed node (do NOT fetch to the driver yet)
    ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
    assert ready
    cluster.remove_node(node)
    # resources for the rerun must exist: revive the custom resource
    cluster.add_node(num_cpus=2, resources={"ephemeral": 4.0})
    arr = ray_tpu.get(ref, timeout=60)  # fetch fails -> reconstructs
    assert float(arr[123_456]) == 123_456.0


def test_kv_survives_head_restart(tmp_path, shutdown_only):
    """Durable KV backend: the internal KV (function table, Serve/Tune
    metadata analogue) survives a head restart (reference: GCS fault
    tolerance with a Redis store, tests/test_gcs_fault_tolerance.py)."""
    import ray_tpu

    ray_tpu.shutdown()  # a prior test may have left a runtime up
    store = str(tmp_path / "gcs_store")
    ray_tpu.init(num_cpus=1, _kv_store_path=store)
    client = ray_tpu._private.worker.get_client()
    client.kv_put(b"durable_key", b"v1")
    client.kv_put(b"temp_key", b"x")
    client.kv_del(b"temp_key")
    client.kv_put(b"durable_key2", b"v2", overwrite=True)
    ray_tpu.shutdown()

    # "restarted head": fresh hub pointed at the same store
    ray_tpu.init(num_cpus=1, _kv_store_path=store)
    client = ray_tpu._private.worker.get_client()
    assert client.kv_get(b"durable_key") == b"v1"
    assert client.kv_get(b"durable_key2") == b"v2"
    assert client.kv_get(b"temp_key") is None
    # mutations after recovery persist too (log reopened post-compact)
    client.kv_put(b"durable_key3", b"v3")
    ray_tpu.shutdown()

    ray_tpu.init(num_cpus=1, _kv_store_path=store)
    client = ray_tpu._private.worker.get_client()
    assert client.kv_get(b"durable_key3") == b"v3"


def test_kv_store_tolerates_torn_log_tail(tmp_path, shutdown_only):
    """A crash mid-append leaves a torn record; recovery drops it and
    keeps everything before it."""
    import ray_tpu

    ray_tpu.shutdown()
    store = str(tmp_path / "gcs_store")
    ray_tpu.init(num_cpus=1, _kv_store_path=store)
    client = ray_tpu._private.worker.get_client()
    client.kv_put(b"a", b"1")
    client.kv_put(b"b", b"2")
    ray_tpu.shutdown()

    import os

    log = os.path.join(store, "kv.log")
    # simulate crash: append garbage half-record
    with open(log, "ab") as f:
        f.write(b"\xff\xff\xff\x7f partial")

    ray_tpu.init(num_cpus=1, _kv_store_path=store)
    client = ray_tpu._private.worker.get_client()
    assert client.kv_get(b"a") == b"1"
    assert client.kv_get(b"b") == b"2"


def test_kv_store_exclusive_lock(tmp_path):
    """Two hubs must not share one durable store (the second would
    truncate the first's log)."""
    from ray_tpu._private.store import FileKvStore

    store = str(tmp_path / "locked_store")
    first = FileKvStore(store)
    first.load()
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="already owned"):
        FileKvStore(store)
    first.close()
    second = FileKvStore(store)  # released lock: reopenable
    second.load()
    second.close()
