"""SLICE placement-group tests: ICI-topology-aware chip reservation.

The TPU-native strategy the reference approximates with pod-name gang
resources (reference python/ray/_private/accelerators/tpu.py:352-375).
Covers: contiguous reservation on a line and a 2D mesh, fragmentation
correctly failing, unknown topology rejected at creation, tasks pinned
to their bundle's reserved chips, and get_current_placement_group.
"""

import os

import pytest

import ray_tpu
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.placement_group import get_current_placement_group


@pytest.fixture
def slice_cluster(monkeypatch):
    monkeypatch.setenv("TPU_TOPOLOGY", "1x8")
    ctx = ray_tpu.init(
        num_cpus=4, num_tpus=8, max_workers=4, ignore_reinit_error=True
    )
    yield ctx
    ray_tpu.shutdown()


def _pg_entry(pg):
    return placement_group_table()[pg.id.hex()]


def _coords_1x8(chip):
    return (0, chip)


def _is_connected(chips, coords):
    """BFS connectivity over unit-step mesh adjacency."""
    chips = set(chips)
    if not chips:
        return False
    seen = {next(iter(chips))}
    frontier = list(seen)
    pos = {coords(c): c for c in chips}
    while frontier:
        c = frontier.pop()
        base = coords(c)
        for dim in range(len(base)):
            for d in (-1, 1):
                nb = list(base)
                nb[dim] += d
                n = pos.get(tuple(nb))
                if n is not None and n not in seen:
                    seen.add(n)
                    frontier.append(n)
    return seen == chips


def test_slice_reserves_contiguous_chips(slice_cluster):
    pg = placement_group([{"TPU": 2}, {"TPU": 2}], strategy="SLICE")
    assert pg.wait(10)
    entry = _pg_entry(pg)
    chips0, chips1 = entry["bundle_chips"]
    assert len(chips0) == 2 and len(chips1) == 2
    # each bundle's chips are ICI-connected, and the whole reservation
    # is one contiguous run on the 1x8 line
    assert _is_connected(chips0, _coords_1x8)
    assert _is_connected(chips1, _coords_1x8)
    assert _is_connected(chips0 + chips1, _coords_1x8)
    remove_placement_group(pg)


def test_slice_2d_mesh(monkeypatch):
    monkeypatch.setenv("TPU_TOPOLOGY", "2x4")
    ray_tpu.init(num_cpus=4, num_tpus=8, max_workers=4,
                 ignore_reinit_error=True)
    try:
        pg = placement_group([{"TPU": 4}], strategy="SLICE")
        assert pg.wait(10)
        (chips,) = _pg_entry(pg)["bundle_chips"]
        assert len(chips) == 4

        def coords(c):
            return (c // 4, c % 4)  # row-major 2x4

        assert _is_connected(chips, coords)
        remove_placement_group(pg)
    finally:
        ray_tpu.shutdown()


def test_slice_fragmented_fails(slice_cluster):
    # carve the 1x8 line into 0-1 / 2-5 / 6-7, free the ends, and ask
    # for 4 contiguous: {0,1,6,7} has no 4-path, so the PG must stay
    # pending (NOT silently spread across the gap)
    pg_a = placement_group([{"TPU": 2}], strategy="SLICE")
    assert pg_a.wait(10)
    pg_mid = placement_group([{"TPU": 4}], strategy="SLICE")
    assert pg_mid.wait(10)
    remove_placement_group(pg_a)
    import time

    time.sleep(0.2)  # removal is async; let the chips return
    pg_frag = placement_group([{"TPU": 4}], strategy="SLICE")
    assert not pg_frag.wait(2)
    # freeing the middle makes it feasible again
    remove_placement_group(pg_mid)
    assert pg_frag.wait(10)
    chips = _pg_entry(pg_frag)["bundle_chips"][0]
    assert _is_connected(chips, _coords_1x8)
    remove_placement_group(pg_frag)


def test_slice_rejected_without_topology(monkeypatch):
    monkeypatch.delenv("TPU_TOPOLOGY", raising=False)
    monkeypatch.delenv("TPU_CHIP_COORDS", raising=False)
    # 3 chips: no default topology => SLICE must be rejected loudly
    ray_tpu.init(num_cpus=2, num_tpus=3, max_workers=2,
                 ignore_reinit_error=True)
    try:
        with pytest.raises(ValueError, match="topology"):
            placement_group([{"TPU": 1}], strategy="SLICE")
    finally:
        ray_tpu.shutdown()


def test_slice_rejects_fractional_chips(slice_cluster):
    with pytest.raises(ValueError, match="whole TPU"):
        placement_group([{"TPU": 0.5}], strategy="SLICE")


def test_task_runs_on_reserved_chips(slice_cluster):
    pg = placement_group([{"TPU": 2}, {"TPU": 2}], strategy="SLICE")
    assert pg.wait(10)
    entry = _pg_entry(pg)

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 2})
    def visible():
        return sorted(
            int(c) for c in os.environ["TPU_VISIBLE_CHIPS"].split(",")
        )

    for idx in (0, 1):
        got = ray_tpu.get(
            visible.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    pg, placement_group_bundle_index=idx
                )
            ).remote(),
            timeout=60,
        )
        assert got == sorted(entry["bundle_chips"][idx])
    remove_placement_group(pg)


def test_get_current_placement_group(slice_cluster):
    assert get_current_placement_group() is None  # driver: not in a PG
    pg = placement_group([{"CPU": 1, "TPU": 1}], strategy="SLICE")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1, resources={"TPU": 1})
    def who():
        cur = get_current_placement_group()
        return None if cur is None else cur.id.hex()

    got = ray_tpu.get(
        who.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
        ).remote(),
        timeout=60,
    )
    assert got == pg.id.hex()
    remove_placement_group(pg)


def test_whole_host_slice_task_spawns_worker(slice_cluster):
    """A SLICE PG reserving ALL chips empties the node free pool; tasks
    into its bundle must still trigger a worker spawn (chips come from
    the bundle, not the pool)."""
    pg = placement_group([{"TPU": 8}], strategy="SLICE")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 8})
    def visible():
        return sorted(
            int(c) for c in os.environ["TPU_VISIBLE_CHIPS"].split(",")
        )

    got = ray_tpu.get(
        visible.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
        ).remote(),
        timeout=60,
    )
    assert got == list(range(8))
    remove_placement_group(pg)


def test_slice_chips_return_after_worker_death(slice_cluster):
    """PG-reserved chips survive their worker's death reserved (not
    leaked into the node free pool) and serve the next bundle task."""
    pg = placement_group([{"TPU": 2}], strategy="SLICE")
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 2}, max_retries=0)
    def crash():
        os._exit(1)

    @ray_tpu.remote(num_cpus=0, resources={"TPU": 2})
    def visible():
        return sorted(
            int(c) for c in os.environ["TPU_VISIBLE_CHIPS"].split(",")
        )

    strat = PlacementGroupSchedulingStrategy(pg, 0)
    with pytest.raises(Exception):
        ray_tpu.get(crash.options(scheduling_strategy=strat).remote(),
                    timeout=60)
    got = ray_tpu.get(
        visible.options(scheduling_strategy=strat).remote(), timeout=60
    )
    assert got == sorted(_pg_entry(pg)["bundle_chips"][0])
    remove_placement_group(pg)


def test_slice_mixed_layout_fragmented_host(slice_cluster):
    """Mixed packing (case 3): several bundles share one host when the
    host's free chips are fragmented — no single path covers the whole
    gang (case 1) and there are fewer hosts than bundles (case 2).
    Layout: carve 1x8 into {0,1} {2,3} {4,5} {6,7} with holes at {2,3}
    and ask for three 2-chip bundles."""
    import time

    edge = placement_group([{"TPU": 2}], strategy="SLICE")
    assert edge.wait(10)
    hole = placement_group([{"TPU": 2}], strategy="SLICE")
    assert hole.wait(10)
    hole_chips = _pg_entry(hole)["bundle_chips"][0]
    assert len(hole_chips) == 2
    # free the edge allocation: the hole now sits MID-line, free chips
    # split into runs of 2 and 4 — no contiguous 6-path exists
    remove_placement_group(edge)
    time.sleep(0.2)

    pg = placement_group([{"TPU": 2}, {"TPU": 2}, {"TPU": 2}],
                         strategy="SLICE")
    assert pg.wait(10), "mixed packing must place 3x2 around the hole"
    entry = _pg_entry(pg)
    chips = entry["bundle_chips"]
    assert [len(c) for c in chips] == [2, 2, 2]
    flat = [c for chunk in chips for c in chunk]
    assert len(set(flat)) == 6 and not (set(flat) & set(hole_chips))
    for chunk in chips:
        assert _is_connected(chunk, _coords_1x8)
    remove_placement_group(pg)
    remove_placement_group(hole)
    time.sleep(0.2)


def test_slice_mixed_layout_prefers_per_host_ranks(slice_cluster):
    """When one bundle per host IS feasible it stays preferred; mixed
    packing only kicks in past it (here: single host, 2 bundles whose
    total fits contiguously -> case 1, adjacent chunks)."""
    pg = placement_group([{"TPU": 2}, {"TPU": 2}], strategy="SLICE")
    assert pg.wait(10)
    chips = _pg_entry(pg)["bundle_chips"]
    flat = [c for chunk in chips for c in chunk]
    assert _is_connected(flat, _coords_1x8)  # one contiguous 4-path
    remove_placement_group(pg)
