"""Fused lm-head cross-entropy kernel (ops/pallas_ce.py) — interpret
mode on CPU, the pattern of tests/test_pallas_attention.py."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import LLAMA_TINY, llama
from ray_tpu.ops.pallas_ce import fused_cross_entropy, xla_cross_entropy


@pytest.fixture(scope="module")
def problem():
    N, D, V = 256, 128, 1024
    kx, kw, kt = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (N, D), jnp.float32) * 0.5
    w = jax.random.normal(kw, (D, V), jnp.float32) * 0.05
    t = jax.random.randint(kt, (N,), 0, V)
    return x, w, t


def test_forward_matches_xla(problem):
    x, w, t = problem
    np.testing.assert_allclose(
        np.asarray(fused_cross_entropy(x, w, t)),
        np.asarray(xla_cross_entropy(x, w, t)),
        atol=5e-6,
    )


def test_gradients_match_xla(problem):
    x, w, t = problem

    gx, gw = jax.grad(
        lambda x_, w_: jnp.mean(fused_cross_entropy(x_, w_, t)),
        argnums=(0, 1),
    )(x, w)
    rx, rw = jax.grad(
        lambda x_, w_: jnp.mean(xla_cross_entropy(x_, w_, t)),
        argnums=(0, 1),
    )(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw), atol=1e-6)


def test_vocab_block_fallback():
    # V=384: block 512 doesn't divide; picks 128
    kx, kw, kt = jax.random.split(jax.random.PRNGKey(1), 3)
    x = jax.random.normal(kx, (128, 64), jnp.float32)
    w = jax.random.normal(kw, (64, 384), jnp.float32) * 0.1
    t = jax.random.randint(kt, (128,), 0, 384)
    np.testing.assert_allclose(
        np.asarray(fused_cross_entropy(x, w, t)),
        np.asarray(xla_cross_entropy(x, w, t)),
        atol=5e-6,
    )


def test_llama_loss_fused_matches_xla():
    """End-to-end: llama.loss_fn(ce_impl='fused') == the XLA path,
    values and grads (LLAMA_TINY, fp32 to keep the comparison tight)."""
    cfg_x = dataclasses.replace(LLAMA_TINY, dtype=jnp.float32)
    cfg_f = dataclasses.replace(cfg_x, ce_impl="fused")
    params = llama.init_params(jax.random.PRNGKey(0), cfg_x)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 65), 0,
                                cfg_x.vocab_size)
    mask = jnp.ones((2, 65), jnp.float32).at[:, -5:].set(0.0)
    batch = {"tokens": tokens, "mask": mask}

    lx = llama.loss_fn(params, batch, cfg_x)
    lf = llama.loss_fn(params, batch, cfg_f)
    np.testing.assert_allclose(float(lf), float(lx), rtol=1e-5)

    gx = jax.grad(lambda p: llama.loss_fn(p, batch, cfg_x))(params)
    gf = jax.grad(lambda p: llama.loss_fn(p, batch, cfg_f))(params)
    for path_x, path_f in zip(
        jax.tree.leaves(gx), jax.tree.leaves(gf)
    ):
        np.testing.assert_allclose(
            np.asarray(path_f), np.asarray(path_x), atol=2e-5
        )
