"""Actor API tests.

Modeled on the reference's python/ray/tests/test_actor.py and
test_actor_failures.py: lifecycle, ordering, named actors, async
actors, concurrency, kill/restart semantics.
"""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, TaskError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, k=1):
        self.n += k
        return self.n

    def value(self):
        return self.n


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6


def test_actor_ctor_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.value.remote()) == 100


def test_actor_call_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    assert ray_tpu.get(refs) == list(range(1, 51))


def test_actor_ctor_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise RuntimeError("ctor failed")

        def f(self):
            return 1

    b = Bad.remote()
    with pytest.raises((TaskError, ActorDiedError)):
        ray_tpu.get(b.f.remote(), timeout=20)


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class A:
        def boom(self):
            raise ValueError("method boom")

    a = A.remote()
    with pytest.raises(TaskError, match="method boom"):
        ray_tpu.get(a.boom.remote())


def test_named_actor(ray_start_regular):
    c = Counter.options(name="counter1").remote()
    ray_tpu.get(c.inc.remote())
    again = ray_tpu.get_actor("counter1")
    assert ray_tpu.get(again.value.remote()) == 1


def test_named_actor_duplicate(ray_start_regular):
    a = Counter.options(name="dup").remote()
    ray_tpu.get(a.inc.remote())
    with pytest.raises(ValueError):
        Counter.options(name="dup").remote()


def test_get_actor_missing(ray_start_regular):
    with pytest.raises(ValueError):
        ray_tpu.get_actor("nope")


def test_kill_actor(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.inc.remote(), timeout=20)


def test_actor_handle_passing(ray_start_regular):
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.inc.remote(10))

    assert ray_tpu.get(use.remote(c)) == 10
    assert ray_tpu.get(c.value.remote()) == 10


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, i):
            import asyncio

            await asyncio.sleep(0.01)
            return i * 2

    a = AsyncWorker.remote()
    assert ray_tpu.get([a.work.remote(i) for i in range(8)]) == [i * 2 for i in range(8)]


def test_max_concurrency_threads(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Slow:
        def work(self):
            time.sleep(0.3)
            return 1

    s = Slow.remote()
    ray_tpu.get(s.work.remote(), timeout=30)  # wait for spawn + ctor
    t0 = time.time()
    ray_tpu.get([s.work.remote() for _ in range(4)])
    # 4 concurrent 0.3s calls should take well under 4*0.3s
    assert time.time() - t0 < 1.0


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.n = 0

        def pid(self):
            import os

            return os.getpid()

        def inc(self):
            self.n += 1
            return self.n

    p = Phoenix.remote()
    pid1 = ray_tpu.get(p.pid.remote())
    ray_tpu.kill(p, no_restart=False)
    # restarted actor loses state but accepts new calls
    deadline = time.time() + 30
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(p.pid.remote(), timeout=10)
            break
        except (ActorDiedError, ray_tpu.exceptions.GetTimeoutError):
            time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1
    assert ray_tpu.get(p.inc.remote()) == 1  # state reset


def test_actor_pool(ray_start_regular):
    from ray_tpu.util import ActorPool

    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return 2 * x

    pool = ActorPool([Doubler.remote(), Doubler.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [2 * i for i in range(8)]


def test_kill_pending_actor(ray_start_regular):
    """Killing a queued (not yet scheduled) actor cancels creation (review finding)."""

    @ray_tpu.remote
    def blocker():
        time.sleep(5)

    @ray_tpu.remote(num_cpus=2)
    class Big:
        def ping(self):
            return 1

    b1, b2 = blocker.remote(), blocker.remote()
    time.sleep(0.5)
    a = Big.remote()  # cannot schedule: both CPUs busy
    ray_tpu.kill(a)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(a.ping.remote(), timeout=20)


def test_was_current_actor_reconstructed(ray_start_regular):
    """Restarted incarnations see the flag (reference:
    runtime_context.was_current_actor_reconstructed)."""
    import os

    @ray_tpu.remote(max_restarts=1)
    class A:
        def flag(self):
            return ray_tpu.get_runtime_context().was_current_actor_reconstructed

        def die(self):
            os._exit(1)

    a = A.remote()
    assert ray_tpu.get(a.flag.remote()) is False
    a.die.remote()
    import time

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if ray_tpu.get(a.flag.remote(), timeout=10) is True:
                break
        except Exception:
            time.sleep(0.2)
    else:
        raise AssertionError("restarted actor never reported the flag")
