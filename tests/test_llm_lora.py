"""LoRA adapter multiplexing for LLM serving (reference: ray.llm
LoraConfig + dynamic_lora_loading_path + serve model multiplexing)."""

import numpy as np
import pytest

import ray_tpu  # noqa: F401  (conftest env)
from ray_tpu.llm import (
    LLMConfig,
    LLMServer,
    LlamaEngine,
    apply_lora,
    load_lora_adapter,
)
from ray_tpu.models import llama

CFG = llama.LLAMA_TINY
PROMPT = [1, 2, 3]


def _base_params():
    import jax

    # LLMConfig.load_params() with no checkpoint = init_params(key 0)
    return llama.init_params(jax.random.PRNGKey(0), CFG)


def _random_lm_head_adapter(path, seed):
    rng = np.random.default_rng(seed)
    delta = rng.normal(size=(CFG.dim, CFG.vocab_size)).astype(np.float32)
    np.savez(path, **{"lm_head.delta": delta})


def _expected_tokens(adapter_path, n=3):
    folded = apply_lora(_base_params(), load_lora_adapter(adapter_path))
    eng = LlamaEngine(CFG, folded, max_batch=2, max_seq=64)
    return eng.generate(PROMPT, max_tokens=n)


def test_apply_lora_folds_factored_and_delta(tmp_path):
    params = _base_params()
    rng = np.random.default_rng(0)
    lm = np.asarray(params["lm_head"], np.float32)
    a = rng.normal(size=(lm.shape[0], 4)).astype(np.float32) * 0.1
    b = rng.normal(size=(4, lm.shape[1])).astype(np.float32) * 0.1
    delta_norm = rng.normal(size=np.asarray(params["final_norm"]).shape).astype(np.float32)

    path = tmp_path / "ad.npz"
    np.savez(path, **{
        "lm_head.A": a, "lm_head.B": b, "final_norm.delta": delta_norm,
    })
    folded = apply_lora(params, load_lora_adapter(str(path)), scale=2.0)
    np.testing.assert_allclose(
        np.asarray(folded["lm_head"]), lm + 2.0 * (a @ b), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(folded["final_norm"]),
        np.asarray(params["final_norm"]) + 2.0 * delta_norm,
        rtol=1e-5,
    )
    # unadapted leaves are SHARED, not copied
    assert folded["embed"] is params["embed"]
    # unknown target raises
    np.savez(tmp_path / "bad.npz", **{"nope.delta": delta_norm})
    with pytest.raises(ValueError, match="unknown parameter"):
        apply_lora(params, load_lora_adapter(str(tmp_path / "bad.npz")))


@pytest.fixture(scope="module")
def lora_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("adapters")
    for name, seed in (("ad_a", 1), ("ad_b", 2), ("ad_c", 3)):
        _random_lm_head_adapter(d / f"{name}.npz", seed)
    return str(d)


def test_server_routes_by_adapter(lora_dir):
    server = LLMServer(LLMConfig(
        model_config=CFG,
        max_batch_size=4,
        max_seq_len=64,
        lora_config={
            "dynamic_lora_loading_path": lora_dir,
            "max_adapters_per_replica": 2,
        },
    ))
    base = server.generate(PROMPT, max_tokens=3)
    out_a = server.generate(PROMPT, max_tokens=3, adapter_id="ad_a")
    out_b = server.generate(PROMPT, max_tokens=3, adapter_id="ad_b")
    # each adapter's output equals an engine running manually-folded
    # weights (the multiplexed engines really serve folded models)
    assert out_a == _expected_tokens(f"{lora_dir}/ad_a.npz")
    assert out_b == _expected_tokens(f"{lora_dir}/ad_b.npz")
    assert out_a != base and out_b != base and out_a != out_b
    # loaded ids visible to the serve multiplex registry
    from ray_tpu.serve.multiplex import registered_model_ids

    assert {"ad_a", "ad_b"} <= set(registered_model_ids())
    # base engine still serves "" requests
    assert server.generate(PROMPT, max_tokens=3) == base
    # openai-style "model" naming the base model routes to base
    out = server({"prompt_ids": PROMPT, "max_tokens": 3, "model": "base"})
    assert out["token_ids"] == base
    # path traversal in adapter ids is rejected
    with pytest.raises(Exception, match="invalid adapter id"):
        server.generate(PROMPT, max_tokens=1, adapter_id="../evil")
    server.shutdown()
    # shutdown drops the multiplex registration
    from ray_tpu.serve.multiplex import registered_model_ids

    assert not ({"ad_a", "ad_b"} & set(registered_model_ids()))


def test_adapter_lru_eviction(lora_dir):
    server = LLMServer(LLMConfig(
        model_config=CFG,
        max_batch_size=4,
        max_seq_len=64,
        lora_config={
            "dynamic_lora_loading_path": lora_dir,
            "max_adapters_per_replica": 2,
        },
    ))
    out = {}
    for aid in ("ad_a", "ad_b", "ad_c"):
        out[aid] = server.generate(PROMPT, max_tokens=2, adapter_id=aid)
    live = [aid for aid in server._engines if aid]
    assert len(live) <= 2, live
    assert "ad_a" not in live  # oldest evicted
    # evicted adapter reloads transparently and reproduces its output
    assert server.generate(PROMPT, max_tokens=2, adapter_id="ad_a") == out["ad_a"]
    server.shutdown()


def test_openai_completions_surface(lora_dir):
    """OpenAI-style completion bodies against the base model and a LoRA
    adapter (reference: build_openai_app router)."""
    from ray_tpu.llm import LLMConfig, OpenAIServer

    server = OpenAIServer(LLMConfig(
        model_config=CFG,
        model_id="tiny-llama",
        max_batch_size=4,
        max_seq_len=64,
        lora_config={"dynamic_lora_loading_path": lora_dir},
    ))
    try:
        out = server({"model": "tiny-llama", "prompt": PROMPT, "max_tokens": 3})
        assert out["object"] == "text_completion"
        assert out["usage"] == {
            "prompt_tokens": 3, "completion_tokens": 3, "total_tokens": 6,
        }
        base_toks = out["choices"][0]["token_ids"]
        assert len(base_toks) == 3
        out_a = server({"model": "ad_a", "prompt": PROMPT, "max_tokens": 3})
        assert out_a["choices"][0]["token_ids"] == _expected_tokens(
            f"{lora_dir}/ad_a.npz"
        )
        assert out_a["model"] == "ad_a"
    finally:
        server.shutdown()
