"""True compiled graphs: resident actor exec loops over shm ring
channels — execute() must cost ZERO scheduler round trips (reference:
compiled_dag_node.py:193 do_exec_tasks + pre-allocated channels)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@ray_tpu.remote
class Doubler:
    def run(self, x):
        return x * 2.0


@ray_tpu.remote
class AddOne:
    def run(self, x):
        return x + 1.0


def _num_task_events():
    return len(ray_tpu._private.worker.get_client().list_state("tasks"))


def test_channel_pipeline_zero_scheduler_roundtrips(ray_start_4_cpus):
    a, b = Doubler.remote(), AddOne.remote()
    with InputNode() as inp:
        dag = b.run.bind(a.run.bind(inp).with_shm_channel((4,))).with_shm_channel((4,))
    compiled = dag.experimental_compile(max_inflight_executions=4)
    assert compiled._channel_mode
    # warm: first execute after loops spin up
    out = compiled.execute(np.ones(4, np.float32)).get(timeout=30)
    np.testing.assert_allclose(out, np.full(4, 3.0))

    before = _num_task_events()
    refs = [
        compiled.execute(np.full(4, float(i), np.float32)) for i in range(8)
    ]
    outs = [r.get(timeout=30) for r in refs]
    after = _num_task_events()
    assert after == before, "execute() must not submit scheduler tasks"
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o, np.full(4, 2.0 * i + 1.0))
    compiled.teardown()


def test_channel_multi_output(ray_start_4_cpus):
    a, b = Doubler.remote(), AddOne.remote()
    with InputNode() as inp:
        dag = MultiOutputNode(
            [
                a.run.bind(inp).with_shm_channel((2,)),
                b.run.bind(inp).with_shm_channel((2,)),
            ]
        )
    compiled = dag.experimental_compile()
    assert compiled._channel_mode
    out = compiled.execute(np.array([1.0, 2.0], np.float32)).get(timeout=30)
    np.testing.assert_allclose(out[0], [2.0, 4.0])
    np.testing.assert_allclose(out[1], [2.0, 3.0])
    compiled.teardown()


def test_unannotated_graph_falls_back_to_legacy(ray_start_4_cpus):
    a = Doubler.remote()
    with InputNode() as inp:
        dag = a.run.bind(inp)  # no channel hint
    compiled = dag.experimental_compile()
    assert not compiled._channel_mode
    assert compiled.execute(np.ones(2)).get(timeout=30)[0] == 2.0


def test_actor_usable_after_teardown(ray_start_4_cpus):
    a = Doubler.remote()
    with InputNode() as inp:
        dag = a.run.bind(inp).with_shm_channel((2,))
    compiled = dag.experimental_compile()
    out = compiled.execute(np.ones(2, np.float32)).get(timeout=30)
    np.testing.assert_allclose(out, [2.0, 2.0])
    compiled.teardown()
    # the resident loop released the actor: plain calls work again
    assert ray_tpu.get(a.run.remote(np.ones(2)), timeout=30)[0] == 2.0


def test_out_of_order_get_rejected(ray_start_4_cpus):
    a = Doubler.remote()
    with InputNode() as inp:
        dag = a.run.bind(inp).with_shm_channel((2,))
    compiled = dag.experimental_compile()
    r1 = compiled.execute(np.ones(2, np.float32))
    r2 = compiled.execute(np.ones(2, np.float32))
    with pytest.raises(RuntimeError):
        r2.get(timeout=10)
    r1.get(timeout=10)
    r2.get(timeout=10)
    compiled.teardown()
