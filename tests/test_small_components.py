"""Smaller user-visible components: usage stats, tqdm, widgets, rpdb,
Serve model multiplexing (SURVEY.md §2.2 usage/telemetry, §2.4
debugging/widgets, Serve multiplex.py)."""

import io
import time

import pytest

import ray_tpu


def test_usage_stats_report(ray_start_regular):
    import ray_tpu.data  # records library usage on import
    from ray_tpu._private import usage

    usage.record_library_usage("data")
    usage.record_extra_usage_tag("test_tag", "42")
    report = usage.get_usage_report()
    assert "data" in report["library_usages"]
    assert report["extra_usage_tags"]["test_tag"] == "42"
    assert report["total_num_nodes"] >= 1
    path = usage.write_usage_report(ray_tpu._private.worker._session_dir)
    import json

    with open(path) as f:
        assert json.load(f)["source"] == "ray_tpu"


def test_tqdm_worker_bars(ray_start_regular):
    from ray_tpu.experimental import tqdm_ray

    @ray_tpu.remote
    def work(n):
        bar = tqdm_ray.tqdm(desc="progress", total=n)
        for _ in range(n):
            bar.update(1)
        bar.close()
        return n

    assert ray_tpu.get(work.remote(7)) == 7
    # driver-local bar: iterator protocol
    seen = list(tqdm_ray.tqdm(range(4), desc="local"))
    assert seen == [0, 1, 2, 3]


def test_widgets_html_reprs(ray_start_regular):
    ctx = ray_tpu._private.worker.RuntimeContext()
    html = ctx._repr_html_()
    assert "ray_tpu cluster" in html and "CPU" in html

    import ray_tpu.data as rd

    ds = rd.range(10).map(lambda r: r)
    html = ds._repr_html_()
    assert "Dataset" in html and "plan" in html


@pytest.mark.slow  # interactive-debugger attach: ~32s of connect/poll
def test_rpdb_breakpoint_attach(ray_start_regular):  # waits in this sandbox
    from ray_tpu.util import rpdb

    @ray_tpu.remote
    def buggy():
        x = 41
        rpdb.set_trace()
        return x + 1

    ref = buggy.remote()
    deadline = time.monotonic() + 20
    while not rpdb.list_breakpoints():
        assert time.monotonic() < deadline, "breakpoint never registered"
        time.sleep(0.05)
    out = io.StringIO()
    rpdb.connect(stdin=io.StringIO("p x\nc\n"), stdout=out)
    assert "41" in out.getvalue()
    assert ray_tpu.get(ref, timeout=30) == 42
    assert rpdb.list_breakpoints() == []


def test_serve_multiplexed_model_loading(ray_start_4_cpus):
    from ray_tpu import serve

    loads = []

    @serve.deployment(num_replicas=1)
    class MuxModel:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            return {"id": model_id}

        def __call__(self, x):
            model = self.get_model(serve.get_multiplexed_model_id())
            return (model["id"], x)

    handle = serve.run(MuxModel.bind(), route_prefix=None)
    try:
        r1 = handle.options(multiplexed_model_id="m1").remote(1).result(timeout_s=30)
        assert r1 == ("m1", 1)
        r2 = handle.options(multiplexed_model_id="m2").remote(2).result(timeout_s=30)
        assert r2 == ("m2", 2)
        # LRU eviction: cap is 2; a third id must still work
        r3 = handle.options(multiplexed_model_id="m3").remote(3).result(timeout_s=30)
        assert r3 == ("m3", 3)
    finally:
        serve.shutdown()


def test_serve_multiplex_routing_prefers_holder(ray_start_4_cpus):
    """With 2 replicas, repeated calls for one model id should land on
    the replica that already holds it once the controller has seen it."""
    import os

    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Who:
        @serve.multiplexed(max_num_models_per_replica=4)
        def get_model(self, model_id: str):
            return model_id

        def __call__(self):
            self.get_model(serve.get_multiplexed_model_id())
            return os.getpid()

    handle = serve.run(Who.bind(), route_prefix=None)
    try:
        h = handle.options(multiplexed_model_id="modelA")
        first = h.remote().result(timeout_s=30)
        # give the controller one ping round to learn the model map,
        # then expire the handle's cached routing state
        time.sleep(1.0)
        h._refresh(force=True)
        pids = {h.remote().result(timeout_s=30) for _ in range(6)}
        assert pids == {first}, f"expected affinity to {first}, got {pids}"
    finally:
        serve.shutdown()


def test_joblib_backend_sklearn(ray_start_regular):
    """Ecosystem shim: joblib/sklearn n_jobs parallelism as tasks
    (reference: python/ray/util/joblib/)."""
    import joblib
    import numpy as np

    from ray_tpu.util.joblib import register_ray

    register_ray()

    def cube(x):
        return x ** 3

    with joblib.parallel_backend("ray_tpu"):
        out = joblib.Parallel(n_jobs=2)(joblib.delayed(cube)(i) for i in range(6))
    assert out == [0, 1, 8, 27, 64, 125]

    from sklearn.datasets import make_classification
    from sklearn.ensemble import RandomForestClassifier

    X, y = make_classification(n_samples=120, n_features=6, random_state=0)
    with joblib.parallel_backend("ray_tpu"):
        clf = RandomForestClassifier(n_estimators=6, n_jobs=2, random_state=0)
        clf.fit(X, y)
    assert clf.score(X, y) > 0.9
