"""RLlib tests (pattern: rllib tuned_examples as convergence regression
— a tiny PPO run on CartPole must improve measurably)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import Algorithm, PPOConfig


@pytest.fixture
def algo(ray_start_4_cpus, tmp_path):
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=3e-3, minibatch_size=64, num_epochs=4, entropy_coeff=0.01)
        .debugging(seed=42)
    )
    a = config.build_algo()
    yield a
    a.stop()


def test_ppo_learns_cartpole(algo, tmp_path):
    """Convergence + per-iteration metrics + checkpoint roundtrip +
    action API in one fixture lifetime (each fixture spawns env-runner
    workers that pay a fresh jax import — consolidating keeps the suite
    inside the driver budget without losing assertions)."""
    result = algo.train()
    assert result["training_iteration"] == 1
    assert result["num_env_steps_sampled_lifetime"] == 2 * 2 * 64
    assert np.isfinite(result["policy_loss"])
    assert np.isfinite(result["vf_loss"])
    # Learning is asserted on episode_return_RECENT_mean (episodes that
    # finished during the iteration), not episode_return_mean: the
    # latter is a trailing deque(maxlen=100) which, at this test's
    # budget (~6k steps, <100 episodes completed), is still a LIFETIME
    # mean containing the seed's random-policy episodes — at iteration
    # 12 it reads ~39 while episodes actually being completed average
    # ~90+, so a "+20 over first" bar on the window is structurally
    # unreachable even though PPO is learning fine (it reaches ~72 by
    # iteration 30 and keeps climbing).
    first = last = (
        result["episode_return_recent_mean"]
        if result["num_episodes_recent"] else None
    )
    for i in range(11):
        r = algo.train()
        if first is None and r["num_episodes_recent"] > 0:
            first = r["episode_return_recent_mean"]
        if r["num_episodes_recent"] > 0:
            last = r["episode_return_recent_mean"]
    assert first is not None and last is not None
    # CartPole random policy ~20; after ~6k steps PPO should be well up
    assert last > first + 20, (first, last)

    path = algo.save(str(tmp_path / "ck"))
    it = algo.iteration
    algo.train()
    algo.restore(path)
    assert algo.iteration == it

    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=0)
    assert algo.compute_single_action(obs) in (0, 1)


# --------------------------------------------------------------- IMPALA
@pytest.fixture
def impala_algo(ray_start_4_cpus):
    from ray_tpu.rllib import IMPALAConfig

    config = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=3e-3, entropy_coeff=0.005, updates_per_iteration=8)
        .debugging(seed=42)
    )
    a = config.build_algo()
    yield a
    a.stop()


def test_impala_learns_cartpole(impala_algo, tmp_path):
    """Async actor-learner convergence + metrics + checkpoint roundtrip
    (reference: rllib IMPALA tuned_examples bar)."""
    r = impala_algo.train()
    assert r["training_iteration"] == 1
    # 8 async updates x 2 envs x 64 steps
    assert r["num_env_steps_sampled_lifetime"] == 8 * 2 * 64
    assert np.isfinite(r["policy_loss"]) and np.isfinite(r["vf_loss"])
    # off-policyness is bounded: mean importance ratio stays near 1
    assert 0.5 < r["mean_rho"] < 2.0
    first = last = r["episode_return_mean"] if r["num_episodes"] else None
    for _ in range(11):
        r = impala_algo.train()
        if first is None and r["num_episodes"] > 0:
            first = r["episode_return_mean"]
        if r["num_episodes"] > 0:
            last = r["episode_return_mean"]
    assert first is not None and last is not None
    assert last > first + 20, (first, last)

    path = impala_algo.save(str(tmp_path / "ck"))
    it = impala_algo.iteration
    impala_algo.train()
    impala_algo.restore(path)
    assert impala_algo.iteration == it


def test_vtrace_reduces_to_gae_like_onpolicy():
    """With rho == 1 (on-policy) and no clipping active, V-trace vs
    equals the n-step TD(lambda=1) return recursion."""
    import numpy as np

    from ray_tpu.rllib import vtrace

    T, B = 5, 3
    rng = np.random.default_rng(0)
    behavior = np.zeros((T, B), np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    boot = rng.normal(size=(B,)).astype(np.float32)
    vs, pg = vtrace(behavior, behavior, rewards, dones, values, boot,
                    gamma=0.9, clip_rho=1.0, clip_c=1.0)
    # reference recursion: vs_t = r_t + gamma * vs_{t+1}
    expected = np.zeros((T, B), np.float32)
    nxt = boot
    for t in reversed(range(T)):
        expected[t] = rewards[t] + 0.9 * nxt
        nxt = expected[t]
    np.testing.assert_allclose(np.asarray(vs), expected, rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------------ DQN
def test_dqn_learns_cartpole(ray_start_4_cpus):
    """Off-policy replay + target-network convergence regression
    (reference: dqn tuned_examples bar)."""
    from ray_tpu.rllib import DQNConfig

    a = (
        DQNConfig()
        .environment("CartPole-v1")
        .training(lr=5e-4, updates_per_iteration=16, train_intensity=8,
                  num_steps_sampled_before_learning_starts=500,
                  epsilon_decay_steps=6000, target_network_update_freq=100)
        .debugging(seed=7)
        .build_algo()
    )
    try:
        first = last = None
        for _ in range(21):
            r = a.train()
            if first is None and r["num_episodes"] > 0:
                first = r["episode_return_mean"]
            if r["num_episodes"] > 0:
                last = r["episode_return_mean"]
        assert first is not None and last is not None
        assert last > first + 20, (first, last)
        assert a.compute_single_action([0.0, 0.0, 0.0, 0.0]) in (0, 1)
    finally:
        a.stop()


def test_replay_buffer_ring_and_sampling():
    import numpy as np

    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    buf.add({"x": np.arange(60, dtype=np.int64)})
    assert len(buf) == 60
    buf.add({"x": np.arange(60, 130, dtype=np.int64)})  # wraps: keeps last 100
    assert len(buf) == 100
    sample = buf.sample(500)["x"]
    # oldest 30 entries were overwritten by the ring
    assert sample.min() >= 30 and sample.max() <= 129


def test_prioritized_replay_prefers_high_td():
    import numpy as np

    from ray_tpu.rllib import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, seed=0)
    buf.add({"x": np.arange(64, dtype=np.int64)})
    s = buf.sample(32)
    td = np.where(s["x"] == s["x"][0], 10.0, 0.0)  # one item very surprising
    buf.update_priorities(td)
    hot = int(s["x"][0])
    counts = sum(
        int((buf.sample(64)["x"] == hot).sum()) for _ in range(20)
    )
    # p(hot) ~ 10/(10 + ~32 unsampled at prio 1.0) ~ 0.24 of 1280 draws;
    # uniform would give ~20 — prioritization must dominate clearly
    assert counts > 150, counts
    assert "weights" in s and s["weights"].max() <= 1.0


def test_bc_offline_from_dataset(ray_start_4_cpus):
    """Offline path: behavior cloning from a ray_tpu.data Dataset
    (reference: rllib/algorithms/bc + rllib/offline over Ray Data)."""
    import ray_tpu.data as rdata
    from ray_tpu.rllib import BCConfig

    # expert policy: action = 1 iff obs[0] + obs[1] > 0
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(2000, 4)).astype(np.float32)
    actions = (obs[:, 0] + obs[:, 1] > 0).astype(np.int64)
    ds = rdata.from_items(
        [{"obs": o, "actions": a} for o, a in zip(obs, actions)]
    )
    algo = BCConfig().training(lr=3e-3).build_algo(obs_dim=4, num_actions=2)
    result = algo.train_on_dataset(ds, epochs=25)
    assert result["num_samples_trained"] == 25 * 2000
    assert result["loss"] < 0.25
    test_obs = rng.normal(size=(200, 4)).astype(np.float32)
    preds = np.array([algo.compute_single_action(o) for o in test_obs])
    truth = (test_obs[:, 0] + test_obs[:, 1] > 0).astype(np.int64)
    assert (preds == truth).mean() > 0.9


@pytest.mark.slow  # ~43s convergence run, the suite's single biggest row
def test_sac_learns_pendulum(ray_start_4_cpus):
    """Continuous-control convergence: twin-critic max-entropy SAC on
    Pendulum (reference: sac tuned_examples bar)."""
    import numpy as np

    from ray_tpu.rllib import SACConfig

    a = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=16)
        .training(train_batch_size=128, updates_per_iteration=8,
                  train_intensity=64, hiddens=(128, 128),
                  num_steps_sampled_before_learning_starts=500)
        .debugging(seed=3)
        .build_algo()
    )
    try:
        first = last = None
        for _ in range(16):
            r = a.train()
            if r["num_episodes"] > 0:
                if first is None:
                    first = r["episode_return_mean"]
                last = r["episode_return_mean"]
        assert first is not None and last is not None
        # random policy sits around -1400; learning shows up as a big
        # move toward 0 (full convergence ~-200 takes ~3x longer)
        assert last > first + 350, (first, last)
        assert last > -1050, (first, last)
        # entropy coefficient must have auto-tuned DOWN from 1.0
        assert float(a.log_alpha) < 0.0
        # env-space action: Pendulum's torque range is [-2, 2]
        act = a.compute_single_action(np.zeros(3, np.float32))
        assert act.shape == (1,) and -2.0 <= float(act[0]) <= 2.0
    finally:
        a.stop()


def test_appo_learns_cartpole(ray_start_4_cpus):
    """Async clipped-surrogate convergence (reference: appo
    tuned_examples bar) on the IMPALA actor-learner machinery."""
    from ray_tpu.rllib import APPOConfig

    a = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=64)
        .training(lr=3e-3, updates_per_iteration=8, entropy_coeff=0.01)
        .debugging(seed=5)
        .build_algo()
    )
    try:
        first = last = None
        for _ in range(10):
            r = a.train()
            if r["num_episodes"] > 0:
                if first is None:
                    first = r["episode_return_mean"]
                last = r["episode_return_mean"]
        assert first is not None and last is not None
        assert last > first + 40, (first, last)
        assert a.compute_single_action([0.0, 0.0, 0.0, 0.0]) in (0, 1)
    finally:
        a.stop()


def test_marwil_prefers_high_return_actions(ray_start_regular):
    """MARWIL re-weights imitation by advantage: with a dataset where
    both actions appear equally but one earns higher returns, BC
    (beta=0) stays ambivalent while MARWIL clones the better action
    (reference: marwil/ -- beta=0 degenerates to BC)."""
    import numpy as np

    import ray_tpu.data as rd
    from ray_tpu.rllib import MARWILConfig

    rng = np.random.default_rng(0)
    rows = []
    for _ in range(600):
        obs = rng.normal(size=4).astype(np.float32)
        # same state distribution for both actions; action 1 pays more
        rows.append({"obs": obs, "actions": 0, "returns": 0.0})
        rows.append({"obs": obs, "actions": 1, "returns": 1.0})
    ds = rd.from_items(rows)

    def action_rate(algo):
        test_obs = rng.normal(size=(64, 4)).astype(np.float32)
        return float(
            np.mean([algo.compute_single_action(o) for o in test_obs])
        )

    marwil = MARWILConfig().training(beta=8.0, lr=5e-3).build_algo(4, 2)
    for _ in range(6):
        r = marwil.train_on_dataset(ds, epochs=1)
    assert r["num_samples_trained"] == 1200
    assert action_rate(marwil) > 0.85, "MARWIL should pick the paying action"

    bc_like = MARWILConfig().training(beta=0.0, lr=5e-3).build_algo(4, 2)
    for _ in range(6):
        bc_like.train_on_dataset(ds, epochs=1)
    # beta=0: pure cloning of a 50/50 dataset -> probabilities near-tied
    # (argmax of near-equal logits is float noise; assert the property)
    import jax.numpy as jnp

    from ray_tpu.rllib.core import forward as _fwd

    import jax

    test_obs = rng.normal(size=(64, 4)).astype(np.float32)
    logits, _ = _fwd(bc_like.params, jnp.asarray(test_obs))
    probs = np.asarray(jax.nn.softmax(logits, axis=-1)[:, 1])
    assert float(np.mean(np.abs(probs - 0.5))) < 0.15


def test_cql_offline_beats_behavior_policy(ray_start_regular):
    """Offline RL: conservative Q-learning from RANDOM-policy CartPole
    transitions must produce a far better-than-random greedy policy
    (reference: rllib/algorithms/cql offline path)."""
    import gymnasium as gym

    import ray_tpu.data as rdata
    from ray_tpu.rllib import CQLConfig

    env = gym.make("CartPole-v1")
    rng = np.random.default_rng(0)
    rows = []
    obs, _ = env.reset(seed=0)
    for _ in range(8000):
        a = int(rng.integers(0, 2))
        nobs, r, term, trunc, _ = env.step(a)
        rows.append({
            "obs": np.asarray(obs, np.float32), "actions": a,
            "rewards": float(r), "next_obs": np.asarray(nobs, np.float32),
            "dones": float(term),
        })
        obs = nobs if not (term or trunc) else env.reset()[0]
    ds = rdata.from_items(rows)

    algo = CQLConfig().training(lr=5e-4, cql_alpha=1.0).build_algo(4, 2)
    assert algo.stage_dataset(ds) == 8000
    for _ in range(3):
        m = algo.train(num_updates=500)
    assert np.isfinite(m["loss"]) and m["cql_penalty"] > 0

    returns = []
    for i in range(5):
        o, _ = env.reset(seed=100 + i)
        total = 0.0
        for _ in range(300):
            o, r, term, trunc, _ = env.step(algo.compute_single_action(o))
            total += r
            if term or trunc:
                break
        returns.append(total)
    # random behavior policy scores ~25; offline CQL must far exceed it
    assert float(np.mean(returns)) > 80, returns
