"""RLlib tests (pattern: rllib tuned_examples as convergence regression
— a tiny PPO run on CartPole must improve measurably)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import Algorithm, PPOConfig


@pytest.fixture
def algo(ray_start_4_cpus, tmp_path):
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=3e-3, minibatch_size=64, num_epochs=4, entropy_coeff=0.01)
        .debugging(seed=42)
    )
    a = config.build_algo()
    yield a
    a.stop()


def test_train_iteration_metrics(algo):
    result = algo.train()
    assert result["training_iteration"] == 1
    assert result["num_env_steps_sampled_lifetime"] == 2 * 2 * 64
    assert np.isfinite(result["policy_loss"])
    assert np.isfinite(result["vf_loss"])


def test_ppo_learns_cartpole(algo):
    first = None
    last = None
    for i in range(12):
        r = algo.train()
        if first is None and r["num_episodes"] > 0:
            first = r["episode_return_mean"]
        if r["num_episodes"] > 0:
            last = r["episode_return_mean"]
    assert first is not None and last is not None
    # CartPole random policy ~20; after ~6k steps PPO should be well up
    assert last > first + 20, (first, last)


def test_checkpoint_roundtrip(algo, tmp_path):
    algo.train()
    path = algo.save(str(tmp_path / "ck"))
    it = algo.iteration
    algo.train()
    algo.restore(path)
    assert algo.iteration == it


def test_compute_single_action(algo):
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=0)
    a = algo.compute_single_action(obs)
    assert a in (0, 1)
