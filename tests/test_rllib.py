"""RLlib tests (pattern: rllib tuned_examples as convergence regression
— a tiny PPO run on CartPole must improve measurably)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import Algorithm, PPOConfig


@pytest.fixture
def algo(ray_start_4_cpus, tmp_path):
    config = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=3e-3, minibatch_size=64, num_epochs=4, entropy_coeff=0.01)
        .debugging(seed=42)
    )
    a = config.build_algo()
    yield a
    a.stop()


def test_train_iteration_metrics(algo):
    result = algo.train()
    assert result["training_iteration"] == 1
    assert result["num_env_steps_sampled_lifetime"] == 2 * 2 * 64
    assert np.isfinite(result["policy_loss"])
    assert np.isfinite(result["vf_loss"])


def test_ppo_learns_cartpole(algo):
    first = None
    last = None
    for i in range(12):
        r = algo.train()
        if first is None and r["num_episodes"] > 0:
            first = r["episode_return_mean"]
        if r["num_episodes"] > 0:
            last = r["episode_return_mean"]
    assert first is not None and last is not None
    # CartPole random policy ~20; after ~6k steps PPO should be well up
    assert last > first + 20, (first, last)


def test_checkpoint_roundtrip(algo, tmp_path):
    algo.train()
    path = algo.save(str(tmp_path / "ck"))
    it = algo.iteration
    algo.train()
    algo.restore(path)
    assert algo.iteration == it


def test_compute_single_action(algo):
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=0)
    a = algo.compute_single_action(obs)
    assert a in (0, 1)


# --------------------------------------------------------------- IMPALA
@pytest.fixture
def impala_algo(ray_start_4_cpus):
    from ray_tpu.rllib import IMPALAConfig

    config = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=64)
        .training(lr=3e-3, entropy_coeff=0.005, updates_per_iteration=8)
        .debugging(seed=42)
    )
    a = config.build_algo()
    yield a
    a.stop()


def test_impala_iteration_metrics(impala_algo):
    r = impala_algo.train()
    assert r["training_iteration"] == 1
    # 8 async updates x 2 envs x 64 steps
    assert r["num_env_steps_sampled_lifetime"] == 8 * 2 * 64
    assert np.isfinite(r["policy_loss"]) and np.isfinite(r["vf_loss"])
    # off-policyness is bounded: mean importance ratio stays near 1
    assert 0.5 < r["mean_rho"] < 2.0


def test_impala_learns_cartpole(impala_algo):
    """Async actor-learner convergence regression (reference:
    rllib IMPALA tuned_examples bar)."""
    first = last = None
    for _ in range(12):
        r = impala_algo.train()
        if first is None and r["num_episodes"] > 0:
            first = r["episode_return_mean"]
        if r["num_episodes"] > 0:
            last = r["episode_return_mean"]
    assert first is not None and last is not None
    assert last > first + 20, (first, last)


def test_impala_checkpoint_roundtrip(impala_algo, tmp_path):
    impala_algo.train()
    path = impala_algo.save(str(tmp_path / "ck"))
    it = impala_algo.iteration
    impala_algo.train()
    impala_algo.restore(path)
    assert impala_algo.iteration == it


def test_vtrace_reduces_to_gae_like_onpolicy():
    """With rho == 1 (on-policy) and no clipping active, V-trace vs
    equals the n-step TD(lambda=1) return recursion."""
    import numpy as np

    from ray_tpu.rllib import vtrace

    T, B = 5, 3
    rng = np.random.default_rng(0)
    behavior = np.zeros((T, B), np.float32)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    boot = rng.normal(size=(B,)).astype(np.float32)
    vs, pg = vtrace(behavior, behavior, rewards, dones, values, boot,
                    gamma=0.9, clip_rho=1.0, clip_c=1.0)
    # reference recursion: vs_t = r_t + gamma * vs_{t+1}
    expected = np.zeros((T, B), np.float32)
    nxt = boot
    for t in reversed(range(T)):
        expected[t] = rewards[t] + 0.9 * nxt
        nxt = expected[t]
    np.testing.assert_allclose(np.asarray(vs), expected, rtol=1e-5, atol=1e-5)
