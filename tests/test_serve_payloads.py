"""Zero-copy serve data plane (PR 14): large request/response payloads
ride the direct object plane instead of pickling inline through the hub.

Tier-1 coverage:
  * a request body above RAY_TPU_SERVE_INLINE_MAX reaches the replica
    as a zero-copy memoryview over the mapped segment; bodies at/below
    the threshold stay inline bytes (the codec is size-tiered)
  * the ingress request dict's "body" key spills (one dict level deep)
  * ndarray payloads spill with dtype/shape preserved
  * oversized responses round-trip: the caller receives a memoryview
    whose bytes equal the original
  * HTTP proxy round-trips multi-MiB bodies both ways (guards the
    serve_http_max_body ingress cap — aiohttp's 1 MiB default 413s)
  * ALL members of a @serve.batch batch share ONE bulk fetch
    (payloads.FETCH_CALLS counts fetch round-trips in the replica)
  * RAY_TPU_SERVE_INLINE_MAX=0 disables spilling end to end
  * a traced 1 MiB request shows serve.payload_put/serve.payload_fetch
    spans and the analyze_trace partition stays EXACT
  * chaos: the object agent dying mid-transfer (close_after) degrades
    both the direct put and the direct pull to the hub relay — the
    request still succeeds and nothing is counted drained/dropped
"""

import hashlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve

BIG = 1024 * 1024        # 1 MiB — far above the 64 KiB default threshold
SMALL = 1024             # 1 KiB — stays inline
CHAOS_BODY = 12 * 1024 * 1024  # > one 8 MiB agent chunk, so close_after:1
                               # kills puts AND pulls mid-stream


@pytest.fixture
def serve_ray():
    ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture
def traced_serve(monkeypatch):
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _probe_deployment():
    @serve.deployment
    class TypeProbe:
        def __call__(self, x):
            body = x["body"] if isinstance(x, dict) else x
            if isinstance(body, (bytes, bytearray, memoryview)):
                digest = hashlib.sha1(body).hexdigest()
                n = len(body)
            elif isinstance(body, np.ndarray):
                digest = hashlib.sha1(np.ascontiguousarray(body)).hexdigest()
                n = int(body.nbytes)
            else:
                digest, n = "", -1
            return {"type": type(body).__name__, "n": n, "digest": digest}

    return TypeProbe


# ------------------------------------------------------------ request side
def test_large_request_arrives_zero_copy_small_stays_inline(serve_ray):
    probe = _probe_deployment()
    handle = serve.run(probe.bind())
    big = os.urandom(BIG)
    out = handle.remote(big).result(timeout_s=30)
    assert out["type"] == "memoryview", out
    assert out["n"] == BIG
    assert out["digest"] == hashlib.sha1(big).hexdigest()

    small = os.urandom(SMALL)
    out = handle.remote(small).result(timeout_s=30)
    assert out["type"] == "bytes", out
    assert out["digest"] == hashlib.sha1(small).hexdigest()


def test_dict_body_spills_one_level_deep(serve_ray):
    probe = _probe_deployment()
    handle = serve.run(probe.bind())
    big = os.urandom(BIG)
    req = {"method": "POST", "path": "/x", "body": big, "headers": {}}
    out = handle.remote(req).result(timeout_s=30)
    assert out["type"] == "memoryview", out
    assert out["digest"] == hashlib.sha1(big).hexdigest()


def test_ndarray_request_spills_with_dtype_shape(serve_ray):
    @serve.deployment
    class ArrProbe:
        def __call__(self, a):
            return {
                "type": type(a).__name__,
                "dtype": str(a.dtype),
                "shape": list(a.shape),
                "sum": float(a.sum()),
            }

    handle = serve.run(ArrProbe.bind())
    arr = np.arange(512 * 600, dtype=np.float32).reshape(512, 600)  # ~1.2 MB
    out = handle.remote(arr).result(timeout_s=30)
    assert out["type"] == "ndarray", out
    assert out["dtype"] == "float32"
    assert out["shape"] == [512, 600]
    assert out["sum"] == float(arr.sum())


# ----------------------------------------------------------- response side
def test_large_response_roundtrip_as_memoryview(serve_ray):
    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind())
    big = os.urandom(BIG)
    out = handle.remote(big).result(timeout_s=30)
    # zero-copy contract: large results arrive as views over the
    # mapped response segment, equal byte-for-byte
    assert isinstance(out, memoryview), type(out)
    assert bytes(out) == big

    small = os.urandom(SMALL)
    out = handle.remote(small).result(timeout_s=30)
    assert isinstance(out, bytes), type(out)
    assert out == small


def test_serve_response_large_body(serve_ray):
    @serve.deployment
    class Resp:
        def __call__(self, x):
            return serve.Response(
                bytes(x), content_type="application/x-custom"
            )

    handle = serve.run(Resp.bind())
    big = os.urandom(BIG)
    out = handle.remote(big).result(timeout_s=30)
    assert isinstance(out, serve.Response)
    assert out.content_type == "application/x-custom"
    assert out.body_bytes() == big


def test_http_proxy_multi_mib_roundtrip(serve_ray):
    @serve.deployment
    class HttpEcho:
        def __call__(self, req):
            return req["body"]

    serve.run(HttpEcho.bind(), route_prefix="/payload",
              http_options={"port": 18852})

    import urllib.request

    big = os.urandom(2 * 1024 * 1024)  # over aiohttp's 1 MiB default cap
    deadline = time.time() + 15
    data = None
    while time.time() < deadline:
        try:
            req = urllib.request.Request(
                "http://127.0.0.1:18852/payload", data=big, method="POST"
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                data = r.read()
            break
        except AssertionError:
            raise
        except Exception:
            time.sleep(0.3)  # route table refreshes ~1s after serve.run
    assert data == big


# ------------------------------------------------------------ batch sharing
def test_batch_members_share_one_fetch(serve_ray):
    # the batch-decorated callable must BE the routed target for the
    # deferred shared fetch (a plain __call__ forwarding into a batch
    # method resolves per-request in handle_request instead — correct,
    # just not shared)
    @serve.deployment
    class BatchProbe:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=2.0)
        async def __call__(self, items):
            from ray_tpu.serve._private import payloads

            return [
                {"batch": len(items), "fetches": payloads.FETCH_CALLS,
                 "n": len(it["body"])}
                for it in items
            ]

        def fetches(self):
            from ray_tpu.serve._private import payloads

            return payloads.FETCH_CALLS

    handle = serve.run(BatchProbe.bind())
    before = handle.fetches.remote().result(timeout_s=30)

    results = [None] * 8
    body = os.urandom(BIG)

    def one(i):
        results[i] = handle.remote({"body": body}).result(timeout_s=60)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert all(r is not None and r["n"] == BIG for r in results), results
    after = handle.fetches.remote().result(timeout_s=30)
    # one bulk fetch per BATCH, not per member: distinct fetch-counter
    # values identify distinct batches (the counter bumps once per batch)
    batches = {(r["batch"], r["fetches"]) for r in results}
    assert sum(b for b, _ in batches) == 8, batches
    assert after - before == len(batches), (before, after, batches)
    assert len(batches) < 8, f"no batch coalesced: {batches}"


# ------------------------------------------------------- threshold control
def test_inline_max_zero_disables_spilling(monkeypatch):
    monkeypatch.setenv("RAY_TPU_SERVE_INLINE_MAX", "0")
    ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    try:
        probe = _probe_deployment()
        handle = serve.run(probe.bind())
        big = os.urandom(BIG)
        out = handle.remote(big).result(timeout_s=30)
        # no spill: the body rides the classic inline path and arrives
        # as the pickled bytes object
        assert out["type"] == "bytes", out
        assert out["digest"] == hashlib.sha1(big).hexdigest()
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


# ------------------------------------------------------------------ tracing
def test_payload_spans_and_exact_partition(traced_serve):
    from ray_tpu._private import worker
    from ray_tpu.util.tracing import analyze_trace

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind())
    big = os.urandom(BIG)
    out = handle.remote(big).result(timeout_s=30)
    assert bytes(out) == big

    want = {"serve.route", "serve.payload_put", "serve.payload_fetch"}
    client = worker.get_client()
    deadline = time.monotonic() + 20
    spans = []
    while time.monotonic() < deadline:
        for row in client.list_state("traces"):
            cand = client.list_state("traces", trace_id=row["trace_id"])
            if want <= {s["name"] for s in cand}:
                spans = cand
                break
        if spans:
            break
        time.sleep(0.1)
    assert spans, "no trace carried the payload span chain"

    put = [s for s in spans if s["name"] == "serve.payload_put"]
    fetch = [s for s in spans if s["name"] == "serve.payload_fetch"]
    assert len(put) == 1 and len(fetch) == 1, [s["name"] for s in spans]
    assert int(put[0]["attrs"]["nbytes"]) >= BIG
    assert int(fetch[0]["attrs"]["nbytes"]) >= BIG

    a = analyze_trace(spans)
    stage_sum = sum(v["dur_s"] for v in a["stages"].values())
    assert abs(stage_sum + a["untracked_s"] - a["end_to_end_s"]) < 1e-6
    assert "serve.payload_put" in a["stages"]
    assert "serve.payload_fetch" in a["stages"]
    # the point of the PR: with the body on the object plane, the
    # dominant stage is routing/execution machinery, not a pickle ride
    assert a["dominant_stage"] not in (
        "client.serialize_args", "worker.deserialize_args",
        "worker.serialize_result",
    )


# -------------------------------------------------------------------- chaos
_CHAOS_DRIVER = """
import hashlib, os, sys

import ray_tpu
from ray_tpu import serve

ray_tpu.init(address={addr!r})
from ray_tpu._private import worker

# defeat the same-host file-copy shortcut: force the SOCKET transfer
# paths (direct put / direct pull) that the chaos plan targets
worker._client.hostname = "elsewhere-host"

handle = serve.get_deployment_handle("ChaosEcho")
body = os.urandom({nbytes})
out = handle.remote(body).result(timeout_s=120)
assert len(out) == len(body), (len(out), len(body))
assert hashlib.sha1(bytes(out)).hexdigest() == hashlib.sha1(body).hexdigest()
print("CHAOS_OK", type(out).__name__)
"""


def test_chaos_agent_death_mid_transfer_falls_back_to_relay(monkeypatch):
    """Agent connections die after ONE 8 MiB chunk (close_after:1): a
    12 MiB request's direct put AND the 12 MiB response's direct pull
    both fail mid-stream and degrade to the hub relay. The request
    still succeeds and the serve plane counts nothing drained or
    dropped."""
    monkeypatch.setenv("RAY_TPU_CHAOS_OBJECT_AGENT", "close_after:1")
    ctx = ray_tpu.init(num_cpus=2, max_workers=2, _tcp_hub=True)
    try:
        @serve.deployment
        class ChaosEcho:
            def __call__(self, x):
                return bytes(x)

        serve.run(ChaosEcho.bind())
        addr = ctx.address_info["address"]

        proc = subprocess.run(
            [sys.executable, "-c",
             _CHAOS_DRIVER.format(addr=addr, nbytes=CHAOS_BODY)],
            capture_output=True, text=True, timeout=180,
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "CHAOS_OK" in proc.stdout, proc.stdout

        hub = ray_tpu._private.worker._hub
        events = [
            e for e in hub.events if e["kind"] == "object_transfer_fallback"
        ]
        ops = {e["op"] for e in events}
        assert "put" in ops, events    # request spill degraded to relay
        assert "fetch" in ops, events  # response pull degraded to relay

        from ray_tpu.util.state import summarize_serve

        summary = summarize_serve()
        for dep in summary["deployments"].values():
            assert dep.get("drained", 0) == 0, summary
            assert dep.get("dropped", 0) == 0, summary
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
