"""Collective library tests.

Pattern from the reference: collective logic tested without real
accelerator fabric (python/ray/experimental/collective/conftest.py
AbstractNcclGroup fake; channel/cpu_communicator.py). Here the xla
backend runs on the 8-device virtual CPU mesh and the store backend on
real multi-process workers.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col
from ray_tpu.util.collective.types import ReduceOp


@pytest.fixture
def xla_group():
    name = "xla_test"
    group = col.init_collective_group(4, 0, backend="xla", group_name=name)
    yield group
    col.destroy_collective_group(name)


class TestXlaGroup:
    def test_allreduce_sum(self, xla_group):
        tensors = [np.full((4, 3), float(i)) for i in range(4)]
        out = xla_group.allreduce(tensors)
        for t in out:
            np.testing.assert_allclose(np.asarray(t), np.full((4, 3), 6.0))

    def test_allreduce_ops(self, xla_group):
        tensors = [np.full((2,), float(i + 1)) for i in range(4)]
        from ray_tpu.util.collective.types import AllReduceOptions

        out = xla_group.allreduce(tensors, AllReduceOptions(reduceOp=ReduceOp.MAX))
        np.testing.assert_allclose(np.asarray(out[0]), [4.0, 4.0])
        out = xla_group.allreduce(tensors, AllReduceOptions(reduceOp=ReduceOp.MIN))
        np.testing.assert_allclose(np.asarray(out[0]), [1.0, 1.0])
        out = xla_group.allreduce(tensors, AllReduceOptions(reduceOp=ReduceOp.AVERAGE))
        np.testing.assert_allclose(np.asarray(out[0]), [2.5, 2.5])
        out = xla_group.allreduce(tensors, AllReduceOptions(reduceOp=ReduceOp.PRODUCT))
        np.testing.assert_allclose(np.asarray(out[0]), [24.0, 24.0])

    def test_broadcast(self, xla_group):
        tensors = [np.full((3,), float(i)) for i in range(4)]
        from ray_tpu.util.collective.types import BroadcastOptions

        out = xla_group.broadcast(tensors, BroadcastOptions(root_rank=2))
        for t in out:
            np.testing.assert_allclose(np.asarray(t), [2.0, 2.0, 2.0])

    def test_reduce(self, xla_group):
        from ray_tpu.util.collective.types import ReduceOptions

        tensors = [np.full((2,), 1.0) for _ in range(4)]
        out = xla_group.reduce(tensors, ReduceOptions(root_rank=1))
        np.testing.assert_allclose(np.asarray(out[1]), [4.0, 4.0])
        np.testing.assert_allclose(np.asarray(out[0]), [1.0, 1.0])

    def test_allgather(self, xla_group):
        tensors = [np.full((2,), float(i)) for i in range(4)]
        out = xla_group.allgather(tensors)
        expect = np.stack([np.full((2,), float(i)) for i in range(4)])
        for t in out:
            np.testing.assert_allclose(np.asarray(t), expect)

    def test_reducescatter(self, xla_group):
        # each rank holds the full [8] vector; rank i gets reduced chunk i
        tensors = [np.arange(8, dtype=np.float32) for _ in range(4)]
        out = xla_group.reducescatter(tensors)
        for i, t in enumerate(out):
            np.testing.assert_allclose(
                np.asarray(t), np.arange(8, dtype=np.float32)[2 * i : 2 * i + 2] * 4
            )

    def test_program_cache_reused(self, xla_group):
        tensors = [np.ones((2, 2)) for _ in range(4)]
        xla_group.allreduce(tensors)
        n = len(xla_group._programs)
        xla_group.allreduce([np.full((2, 2), 2.0) for _ in range(4)])
        assert len(xla_group._programs) == n  # same shape -> cached
        xla_group.allreduce([np.ones((3,)) for _ in range(4)])
        assert len(xla_group._programs) == n + 1

    def test_barrier(self, xla_group):
        xla_group.barrier()


def _store_worker(rank, world, group_name, op):
    from ray_tpu.util import collective as c
    from ray_tpu.util.collective.types import (
        RecvOptions,
        SendOptions,
    )

    g = c.init_collective_group(world, rank, backend="store", group_name=group_name)
    data = np.full((4,), float(rank + 1), dtype=np.float32)
    try:
        if op == "allreduce":
            return g.allreduce(data)
        if op == "allgather":
            return g.allgather(data)
        if op == "reducescatter":
            return g.reducescatter(np.arange(4, dtype=np.float32))
        if op == "broadcast":
            from ray_tpu.util.collective.types import BroadcastOptions

            return g.broadcast(data, BroadcastOptions(root_rank=1))
        if op == "barrier":
            g.barrier()
            return rank
        if op == "sendrecv":
            if rank == 0:
                g.send(np.array([42.0]), SendOptions(dst_rank=1))
                return None
            return g.recv(RecvOptions(src_rank=0))
    finally:
        c.destroy_collective_group(group_name)


class TestStoreGroup:
    def _run(self, op, name, world=2):
        f = ray_tpu.remote(_store_worker)
        refs = [f.remote(r, world, name, op) for r in range(world)]
        return ray_tpu.get(refs)

    def test_allreduce(self, ray_start_4_cpus):
        out = self._run("allreduce", "sg_ar")
        for t in out:
            np.testing.assert_allclose(t, np.full((4,), 3.0))

    def test_allgather(self, ray_start_4_cpus):
        out = self._run("allgather", "sg_ag")
        expect = np.stack([np.full((4,), 1.0), np.full((4,), 2.0)])
        np.testing.assert_allclose(out[0], expect)
        np.testing.assert_allclose(out[1], expect)

    def test_reducescatter(self, ray_start_4_cpus):
        out = self._run("reducescatter", "sg_rs")
        np.testing.assert_allclose(out[0], [0.0, 2.0])
        np.testing.assert_allclose(out[1], [4.0, 6.0])

    def test_broadcast(self, ray_start_4_cpus):
        out = self._run("broadcast", "sg_bc")
        for t in out:
            np.testing.assert_allclose(t, np.full((4,), 2.0))

    def test_barrier(self, ray_start_4_cpus):
        assert sorted(self._run("barrier", "sg_b")) == [0, 1]

    def test_sendrecv(self, ray_start_4_cpus):
        out = self._run("sendrecv", "sg_p2p")
        np.testing.assert_allclose(out[1], [42.0])


class TestModuleAPI:
    def test_module_level_functions(self):
        col.init_collective_group(2, 0, backend="xla", group_name="mod_api")
        try:
            assert col.is_group_initialized("mod_api")
            assert col.get_rank("mod_api") == 0
            assert col.get_collective_group_size("mod_api") == 2
            out = col.allreduce([np.ones(2), np.ones(2)], group_name="mod_api")
            np.testing.assert_allclose(np.asarray(out[0]), [2.0, 2.0])
        finally:
            col.destroy_collective_group("mod_api")
        assert not col.is_group_initialized("mod_api")

    def test_nccl_rejected(self):
        with pytest.raises(ValueError, match="NCCL is a GPU backend"):
            col.init_collective_group(2, 0, backend="nccl", group_name="x")

    def test_declarative_create(self, ray_start_4_cpus):
        class W:
            def reduce_val(self, group_name):
                from ray_tpu.util import collective as c

                return c.allreduce(np.array([1.0]), group_name=group_name)

        WA = ray_tpu.remote(W)
        actors = [WA.remote() for _ in range(2)]
        col.create_collective_group(
            actors, 2, [0, 1], backend="store", group_name="decl"
        )
        out = ray_tpu.get([a.reduce_val.remote("decl") for a in actors])
        np.testing.assert_allclose(out[0], [2.0])


def test_xla_group_eager_p2p():
    """Eager send/recv on the single-controller group: send() lands the
    tensor on the destination rank's device; recv(rank) drains that
    rank's mailbox FIFO (was NotImplementedError through round 2)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.util.collective.collective_group.xla_group import XlaGroup
    from ray_tpu.util.collective.types import RecvOptions, SendOptions

    devs = jax.devices()[:4]
    g = XlaGroup(world_size=len(devs), rank=0, group_name="p2p", devices=devs)
    a = jnp.arange(8.0)
    b = jnp.arange(8.0) * 2
    g.send([a], SendOptions(dst_rank=2))
    g.send([b], SendOptions(dst_rank=2))
    out1 = g.recv(RecvOptions(src_rank=2))
    out2 = g.recv(RecvOptions(src_rank=2))
    np.testing.assert_allclose(np.asarray(out1), np.asarray(a))
    np.testing.assert_allclose(np.asarray(out2), np.asarray(b))
    assert out1.devices() == {devs[2]}
    import pytest

    with pytest.raises(RuntimeError):
        g.recv(RecvOptions(src_rank=1))
