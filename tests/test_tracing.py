"""Distributed runtime tracing (util/tracing.py + the span plumbing
through client/hub/worker).

Tier-1 coverage for the self-tracing runtime:
  - trace context propagates client -> task -> nested task across real
    worker processes, and the runtime spans of one submit stitch into a
    single trace with correct parentage,
  - the critical-path analyzer names the dominant stage and its
    per-stage durations (plus the untracked remainder) partition the
    end-to-end latency,
  - error spans carry the exception name,
  - sampling=0 (the default) emits nothing,
  - the chrome-trace export loads as valid JSON with cat="span" rows,
  - sharded hubs attribute ring-wait (shards stamp, the state plane
    emits — GL010-clean funneling).
"""

import json
import os
import time

import pytest


@pytest.fixture
def traced_ray(monkeypatch):
    """A cluster with runtime tracing forced on (sampling 1.0). The env
    must be set before init: the driver's CoreClient reads it at
    construction and spawned workers inherit it."""
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def _find_trace(predicate, deadline_s=15.0):
    """Poll the hub's trace store until one trace's spans satisfy
    `predicate` (span emission is async: records ride the send_async
    batches of three different processes)."""
    client = _client()
    deadline = time.monotonic() + deadline_s
    last = []
    while time.monotonic() < deadline:
        for row in client.list_state("traces"):
            spans = client.list_state("traces", trace_id=row["trace_id"])
            if predicate(spans):
                return spans
            last = spans
        time.sleep(0.1)
    raise AssertionError(
        f"no trace satisfied the predicate; last inspected spans: "
        f"{[(s.get('name'), (s.get('attrs') or {}).get('name')) for s in last]}"
    )


def _by_name(spans, span_name, **attr_filter):
    out = []
    for s in spans:
        if s.get("name") != span_name:
            continue
        attrs = s.get("attrs") or {}
        if all(attrs.get(k) == v for k, v in attr_filter.items()):
            out.append(s)
    return out


def test_one_submit_stitches_across_three_processes(traced_ray, tmp_path):
    """The demo trace: client -> hub -> worker -> nested worker, >= 6
    runtime spans over >= 3 processes, correct parentage, dominant
    stage named by the critical-path analyzer, stage durations + the
    untracked remainder partitioning end-to-end latency."""
    import ray_tpu
    from ray_tpu.util.tracing import analyze_trace

    @ray_tpu.remote
    def warm():
        return 1

    # warm the pool so the demo trace measures execution, not the
    # worker interpreter spawn (spawn gets its own stage span when it
    # IS in the path — not forced here)
    ray_tpu.get([warm.remote() for _ in range(2)])

    @ray_tpu.remote
    def inner():
        time.sleep(0.15)
        return 2

    @ray_tpu.remote
    def outer():
        time.sleep(0.3)
        return ray_tpu.get(inner.remote()) + 1

    def complete(spans, t_min):
        names = {
            (s.get("name"), (s.get("attrs") or {}).get("name"))
            for s in spans
        }
        return (
            ("worker.execute", "outer") in names
            and ("worker.execute", "inner") in names
            and any(s.get("name") == "hub.complete" for s in spans)
            and min(s["start"] for s in spans) >= t_min
        )

    # the structural asserts below hold on every attempt; the 10%
    # untracked bound is a TIMING property that a heavily loaded box
    # can blow (every inter-process hop stretches under contention), so
    # the demo retries with a fresh trace up to 3 times
    analysis = None
    for _attempt in range(3):
        t_min = time.time() - 1.0  # spans are wall-anchored
        assert ray_tpu.get(outer.remote()) == 3
        spans = _find_trace(lambda spans: complete(spans, t_min))
        analysis = analyze_trace(spans)
        if analysis["untracked_s"] <= 0.1 * analysis["end_to_end_s"]:
            break
    assert len(spans) >= 6
    assert len({s["trace_id"] for s in spans}) == 1

    # >= 3 distinct processes: driver, outer's worker, inner's worker
    pids = {s["pid"] for s in spans}
    assert len(pids) >= 3, pids

    # parentage: driver submit is the root; the hub's admit and
    # queue_wait spans hang off it; outer's execute span hangs off the
    # dispatch span; the NESTED submit hangs off outer's execute span
    # (that's context propagation through a real worker process)
    root = next(s for s in spans if s.get("parent_id") is None)
    assert root["name"] == "client.submit"
    admits = [s for s in _by_name(spans, "hub.admit")
              if s["parent_id"] == root["span_id"]]
    scheds = [s for s in _by_name(spans, "hub.sched")
              if s["parent_id"] == root["span_id"]]
    assert admits and scheds
    outer_exec = next(
        s for s in _by_name(spans, "worker.execute", name="outer")
    )
    assert outer_exec["parent_id"] == scheds[0]["span_id"]
    nested_submit = next(
        s for s in _by_name(spans, "client.submit")
        if s["parent_id"] == outer_exec["span_id"]
    )
    inner_exec = next(
        s for s in _by_name(spans, "worker.execute", name="inner")
    )
    assert inner_exec["trace_id"] == root["trace_id"]
    assert inner_exec["pid"] not in (root["pid"], outer_exec["pid"])
    assert nested_submit["pid"] == outer_exec["pid"]

    # critical path: execution dominates (the sleeps), and the staged
    # breakdown partitions end-to-end latency — stages + untracked sum
    # to e2e exactly, with the untracked remainder under 10%
    assert analysis["dominant_stage"] == "execute", analysis
    e2e = analysis["end_to_end_s"]
    assert e2e >= 0.45  # two sleeps stacked
    staged = sum(d["dur_s"] for d in analysis["stages"].values())
    assert abs(staged + analysis["untracked_s"] - e2e) < 1e-6
    assert analysis["untracked_s"] <= 0.1 * e2e, analysis
    assert len(analysis["processes"]) >= 3

    # chrome-trace export: valid JSON, runtime spans render as
    # cat="span" rows beside the task rows
    out = tmp_path / "trace.json"
    ray_tpu.timeline(str(out))
    rows = json.loads(out.read_text())
    span_rows = [r for r in rows if r.get("cat") == "span"]
    assert any(r["name"] == "worker.execute" for r in span_rows)
    assert any(r["name"] == "client.submit" for r in span_rows)
    for r in span_rows:
        assert r["ph"] == "X" and r["dur"] >= 0


def test_error_span_carries_exception_name(traced_ray):
    import ray_tpu

    @ray_tpu.remote
    def boom():
        raise ValueError("nope")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote())

    spans = _find_trace(
        lambda spans: any(
            (s.get("attrs") or {}).get("error") == "ValueError"
            for s in _by_name(spans, "worker.execute")
        )
    )
    err_span = next(
        s for s in _by_name(spans, "worker.execute")
        if (s.get("attrs") or {}).get("error") == "ValueError"
    )
    assert err_span["attrs"]["stage"] == "execute"
    # the flight recorder cross-links the failure to the trace
    events = _client().list_state("events")
    assert any(
        e.get("kind") == "task_failed"
        and e.get("trace_id") == err_span["trace_id"]
        for e in events
    )


def test_sampling_zero_emits_nothing(ray_start_regular):
    """Default env: no trace context on the wire, no runtime spans, no
    traces — the hot path stays untouched."""
    import ray_tpu

    assert os.environ.get("RAY_TPU_TRACING") is None

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2
    client = _client()
    assert client._trace_on is False
    assert client.list_state("traces") == []
    time.sleep(0.4)  # let any stray async span batch land
    assert not [
        e for e in ray_tpu.timeline()
        if e.get("cat") == "span"
    ]


def test_repeated_get_does_not_extend_trace(traced_ray):
    """Re-getting an already-fetched traced ref must not append another
    result_return span: a cached re-access seconds later would stretch
    the finished trace's end-to-end window and dilute every stage share
    (the _trace_refs entry is dropped once a traced get completes)."""
    import ray_tpu

    @ray_tpu.remote
    def f():
        return 1

    ref = f.remote()
    assert ray_tpu.get(ref) == 1
    spans = _find_trace(
        lambda spans: bool(_by_name(spans, "worker.execute", name="f"))
        and bool(_by_name(spans, "client.get"))
    )
    trace_id = spans[0]["trace_id"]
    assert ray_tpu.get(ref) == 1  # served from the local cache
    time.sleep(0.5)  # a stray span batch would have landed by now
    spans2 = _client().list_state("traces", trace_id=trace_id)
    assert len(_by_name(spans2, "client.get")) == 1


def test_ambient_context_traces_without_local_sampling(ray_start_regular):
    """A live trace context must keep stitching even when THIS
    process's sampling is off (client-mode drivers sample while the
    head's env doesn't; the hub/worker span paths are payload-driven,
    so the client gate must consult the ambient context too)."""
    import ray_tpu
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def f():
        return 5

    client = _client()
    assert client._trace_on is False
    with tracing.context(("feedbeef00000000", "cafe000000000000")):
        assert ray_tpu.get(f.remote()) == 5
    spans = _find_trace(
        lambda spans: bool(_by_name(spans, "worker.execute", name="f"))
    )
    assert {s["trace_id"] for s in spans} == {"feedbeef00000000"}
    root = next(s for s in spans if s["name"] == "client.submit")
    assert root["parent_id"] == "cafe000000000000"


def test_actor_call_trace(traced_ray):
    import ray_tpu

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote()) == 1

    spans = _find_trace(
        lambda spans: bool(
            _by_name(spans, "worker.execute", name="bump")
        ) and bool(_by_name(spans, "hub.actor_route"))
    )
    route = _by_name(spans, "hub.actor_route")[0]
    execute = _by_name(spans, "worker.execute", name="bump")[0]
    assert execute["parent_id"] == route["span_id"]
    assert (route.get("attrs") or {}).get("stage") == "queue_wait"


def test_sharded_hub_emits_ring_wait_spans(monkeypatch):
    """shards>1: the owning shard stamps traced frames at decode time
    and the state plane emits the ring-wait span (the shard itself
    never touches the span store — GL010)."""
    monkeypatch.setenv("RAY_TPU_HUB_SHARDS", "4")
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    import ray_tpu

    ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def f():
            return 42

        assert ray_tpu.get(f.remote()) == 42
        spans = _find_trace(
            lambda spans: bool(_by_name(spans, "shard.ring_wait"))
            and bool(_by_name(spans, "worker.execute", name="f"))
        )
        ring = _by_name(spans, "shard.ring_wait")[0]
        assert (ring.get("attrs") or {}).get("stage") == "ring_wait"
        assert ring["end"] >= ring["start"]
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ pure units
def test_analyze_trace_overlap_resolution():
    """Overlapping stage spans partition by precedence: a spawn inside
    the queue wait is charged to spawn, the enveloping client get only
    contributes its tail past the last runtime stage."""
    from ray_tpu.util.tracing import analyze_trace

    def mk(name, stage, a, b, pid=1):
        return {"name": name, "trace_id": "t1", "span_id": name,
                "parent_id": None, "start": a, "end": b, "pid": pid,
                "node_id": "node0", "attrs": {"stage": stage}}

    spans = [
        mk("client.submit", "submit", 0.0, 0.01),
        mk("hub.sched", "queue_wait", 0.01, 0.41),
        mk("hub.worker_spawn", "spawn", 0.11, 0.41),      # inside queue
        mk("worker.execute", "execute", 0.41, 1.41, pid=2),
        mk("client.get", "result_return", 0.0, 1.46),     # envelope
    ]
    out = analyze_trace(spans)
    st = out["stages"]
    assert out["dominant_stage"] == "execute"
    assert abs(st["queue_wait"]["dur_s"] - 0.10) < 1e-9   # minus spawn
    assert abs(st["spawn"]["dur_s"] - 0.30) < 1e-9
    assert abs(st["execute"]["dur_s"] - 1.00) < 1e-9
    assert abs(st["result_return"]["dur_s"] - 0.05) < 1e-9  # tail only
    total = sum(d["dur_s"] for d in st.values()) + out["untracked_s"]
    assert abs(total - out["end_to_end_s"]) < 1e-9
    assert out["untracked_s"] == 0.0


def test_analyze_trace_late_get_not_charged_to_result_return():
    """A get() issued long after the task finished must not book the
    driver's idle time as result_return — the tail is clamped to the
    get span's own start."""
    from ray_tpu.util.tracing import analyze_trace

    def mk(name, stage, a, b):
        return {"name": name, "trace_id": "t2", "span_id": name,
                "parent_id": None, "start": a, "end": b, "pid": 1,
                "node_id": "node0", "attrs": {"stage": stage}}

    out = analyze_trace([
        mk("client.submit", "submit", 0.0, 0.01),
        mk("worker.execute", "execute", 0.01, 0.06),
        mk("client.get", "result_return", 5.0, 5.001),  # 5s later
    ])
    assert out["dominant_stage"] == "execute"
    assert out["stages"]["result_return"]["dur_s"] < 0.01
    assert out["untracked_s"] > 4.0  # the idle gap is reported honestly


def test_span_ids_pooled_and_unique():
    from ray_tpu._private.ids import span_id_hex

    ids = {span_id_hex() for _ in range(5000)}
    assert len(ids) == 5000
    assert all(len(i) == 16 for i in ids)


def test_user_span_durations_survive_wall_step(monkeypatch):
    """Satellite fix: span durations come from time.monotonic() under a
    single per-process wall anchor — a wall-clock step mid-span must
    not warp the duration (GL008's bug class, now linted in this
    file)."""
    from ray_tpu.util import tracing

    recs = []
    monkeypatch.setattr(tracing, "_emit", recs.append)
    monkeypatch.setattr(tracing, "_enabled", True)
    real_time = time.time
    # jump the wall clock backwards by an hour mid-span
    monkeypatch.setattr(time, "time", lambda: real_time() - 3600.0)
    with tracing.span("steady"):
        time.sleep(0.02)
    assert len(recs) == 1
    dur = recs[0]["end"] - recs[0]["start"]
    assert 0.015 <= dur <= 5.0, dur


def test_analyze_trace_malformed_spans_partial_report():
    """A trace truncated by eviction or a crashing process yields a
    PARTIAL report, never an exception: orphan spans analyze fine
    (nothing walks parents), spans with missing/corrupt start/end are
    dropped and counted, zero-duration stages contribute 0s."""
    from ray_tpu.util.tracing import analyze_trace

    def mk(name, stage, a, b, **kw):
        d = {"name": name, "trace_id": "t1", "span_id": name,
             "parent_id": None, "start": a, "end": b, "pid": 1,
             "node_id": "node0", "attrs": {"stage": stage}}
        d.update(kw)
        return d

    spans = [
        # orphan: parent never recorded — must still be charged
        mk("hub.sched", "queue_wait", 0.0, 0.5,
           parent_id="never-recorded"),
        # zero-duration stage: fine, contributes 0s, no crash
        mk("hub.dispatch", "dispatch", 0.5, 0.5),
        mk("worker.execute", "execute", 0.5, 1.1),
        # missing end stamp (producer died mid-span)
        {"name": "torn", "trace_id": "t1", "span_id": "x",
         "start": 0.2, "attrs": {"stage": "execute"}},
        # corrupt stamps
        mk("bad.types", "execute", "not-a-number", 1.0),
        mk("bad.order", "execute", 2.0, 1.0),  # end before start
        # not even a dict
        "garbage",
        None,
    ]
    out = analyze_trace(spans)
    assert out["n_spans"] == len(spans)
    assert out["malformed_spans"] == 5
    assert out["dominant_stage"] == "execute"
    assert abs(out["end_to_end_s"] - 1.1) < 1e-9
    assert abs(out["stages"]["queue_wait"]["dur_s"] - 0.5) < 1e-9
    assert abs(out["stages"]["execute"]["dur_s"] - 0.6) < 1e-9
    assert "dispatch" not in out["stages"] or (
        out["stages"]["dispatch"]["dur_s"] == 0.0
    )


def test_analyze_trace_all_spans_malformed_never_throws():
    from ray_tpu.util.tracing import analyze_trace

    out = analyze_trace([
        {"name": "a"}, {"start": None, "end": None}, 42, "junk",
        {"start": True, "end": True},  # bools are not timestamps
    ])
    assert out["n_spans"] == 5
    assert out["malformed_spans"] == 5
    assert out["end_to_end_s"] == 0.0
    assert out["stages"] == {}
    assert out["dominant_stage"] is None
