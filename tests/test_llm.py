"""LLM library: KV-cache engine correctness vs the full forward,
continuous batching, serving (handle + HTTP + streaming), Data batch
inference, and TP x PP placement sizing (reference:
python/ray/llm/_internal/serve/.../vllm_models.py:123-142)."""

import dataclasses

import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import (
    GenRequest,
    LLMConfig,
    LlamaEngine,
    build_llm_app,
    build_llm_processor,
    save_params_npz,
)
from ray_tpu.models import llama


def tiny_cfg():
    return dataclasses.replace(llama.LLAMA_TINY, remat=False)


@pytest.fixture(scope="module")
def engine_setup():
    import jax

    cfg = tiny_cfg()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_cached_decode_matches_full_forward(engine_setup):
    """Greedy generation with the KV cache must equal naive re-forward
    generation (the engine's correctness invariant)."""
    import jax.numpy as jnp

    cfg, params = engine_setup
    prompt = [5, 17, 99, 3]
    steps = 6

    # naive: full forward each step
    ids = list(prompt)
    for _ in range(steps):
        logits = llama.forward(params, jnp.asarray([ids]), cfg)
        ids.append(int(logits[0, -1].argmax()))
    expected = ids[len(prompt):]

    eng = LlamaEngine(cfg, params, max_batch=2, max_seq=64)
    got = eng.generate(prompt, max_tokens=steps)
    assert got == expected, (got, expected)


def test_continuous_batching_interleaves(engine_setup):
    cfg, params = engine_setup
    eng = LlamaEngine(cfg, params, max_batch=4, max_seq=64)
    reqs = [
        GenRequest(request_id=str(i), prompt_ids=[i + 1, i + 2],
                   max_tokens=4 + i)
        for i in range(6)  # more requests than slots
    ]
    pending = list(reqs)
    while pending or eng.num_active():
        while pending and eng.has_capacity():
            eng.add_request(pending.pop(0))
        eng.step()
    for i, r in enumerate(reqs):
        assert r.done and len(r.generated) == 4 + i

    # single-request result must match the batched run (slot isolation)
    solo = LlamaEngine(cfg, params, max_batch=1, max_seq=64)
    assert solo.generate([1, 2], max_tokens=4) == reqs[0].generated


def test_chunked_prefill_decodes_while_prefilling(engine_setup):
    """A long prompt prefills chunk-by-chunk inside step(); an
    already-active short request keeps emitting tokens DURING that
    prefill (no head-of-line blocking — VERDICT r3 Weak #7)."""
    cfg, params = engine_setup
    eng = LlamaEngine(cfg, params, max_batch=2, max_seq=256,
                      prefill_chunk=16)
    short = GenRequest(request_id="short", prompt_ids=[1, 2],
                       max_tokens=40)
    assert eng.add_request(short)
    # let the short prompt finish prefilling and start decoding
    while not short.generated:
        eng.step()
    long = GenRequest(
        request_id="long", prompt_ids=list(range(1, 200)), max_tokens=4
    )
    assert eng.add_request(long)
    # 199 tokens / 16-token chunks => >= 13 steps of prefill; the short
    # request must make decode progress across those same steps
    decoded_during_prefill = 0
    while long.prefill_pos < len(long.prompt_ids) and not long.done:
        before = len(short.generated)
        eng.step()
        decoded_during_prefill += len(short.generated) - before
    assert decoded_during_prefill >= 10, (
        f"short request starved during long prefill "
        f"({decoded_during_prefill} tokens)"
    )
    while not (short.done and long.done):
        eng.step()
    # chunked prefill must produce the same tokens as one-shot prefill
    solo = LlamaEngine(cfg, params, max_batch=1, max_seq=256,
                       prefill_chunk=256)
    assert solo.generate(list(range(1, 200)), max_tokens=4) == long.generated


def test_slot_growth_beyond_max_batch(engine_setup):
    """More concurrent requests than max_batch: the engine grows by
    cache shards (same compiled programs) up to max_slots."""
    cfg, params = engine_setup
    eng = LlamaEngine(cfg, params, max_batch=2, max_seq=64, max_slots=6)
    reqs = [
        GenRequest(request_id=str(i), prompt_ids=[i + 1], max_tokens=3)
        for i in range(6)
    ]
    for r in reqs:
        assert eng.add_request(r)  # all 6 admitted concurrently
    assert len(eng.shards) == 3
    overflow = GenRequest(request_id="x", prompt_ids=[9], max_tokens=3)
    assert not eng.add_request(overflow)  # max_slots cap holds
    while any(not r.done for r in reqs):
        eng.step()
    solo = LlamaEngine(cfg, params, max_batch=1, max_seq=64)
    for i, r in enumerate(reqs):
        assert r.generated == solo.generate([i + 1], max_tokens=3)


def test_generation_from_checkpoint(engine_setup, tmp_path):
    cfg, params = engine_setup
    path = str(tmp_path / "model.npz")
    save_params_npz(params, path)
    llm_cfg = LLMConfig(model_config=cfg, checkpoint_path=path, max_seq_len=64)
    loaded = llm_cfg.load_params()
    eng = LlamaEngine(cfg, loaded, max_batch=1, max_seq=64)
    ref = LlamaEngine(cfg, params, max_batch=1, max_seq=64)
    assert eng.generate([7, 8, 9], max_tokens=5) == ref.generate(
        [7, 8, 9], max_tokens=5
    )


def test_placement_bundles_tp_pp():
    one = LLMConfig(tensor_parallel_size=4)
    bundles, strategy = one.placement_bundles()
    assert strategy == "PACK" and bundles == [{"TPU": 4.0, "CPU": 1.0}]
    pp = LLMConfig(tensor_parallel_size=4, pipeline_parallel_size=2)
    bundles, strategy = pp.placement_bundles()
    assert strategy == "SPREAD"
    assert bundles == [{"TPU": 4.0, "CPU": 1.0}] * 2


@pytest.fixture(scope="module")
def serve_llm(engine_setup, tmp_path_factory):
    import ray_tpu

    ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    tmp_path = tmp_path_factory.mktemp("llmserve")
    from ray_tpu import serve

    cfg, params = engine_setup
    path = str(tmp_path / "m.npz")
    save_params_npz(params, path)
    llm_cfg = LLMConfig(
        model_config=cfg, checkpoint_path=path,
        max_batch_size=4, max_seq_len=64, accelerator_type="",
    )
    app = build_llm_app(llm_cfg)
    handle = serve.run(
        app, name="llm", route_prefix="/llm",
        http_options={"port": 18931},
    )
    yield handle, cfg, params
    serve.shutdown()
    ray_tpu.shutdown()


def test_serve_generate_and_stream(serve_llm):
    handle, cfg, params = serve_llm
    out = handle.remote({"prompt_ids": [5, 17, 99, 3], "max_tokens": 6}).result()
    assert out["num_generated"] == 6
    # must match local greedy generation (same checkpoint)
    local = LlamaEngine(cfg, params, max_batch=1, max_seq=64)
    assert out["token_ids"] == local.generate([5, 17, 99, 3], max_tokens=6)

    # token-by-token streaming through serve's streaming path
    toks = list(
        handle.options(method_name="generate_stream", stream=True).remote(
            [5, 17, 99, 3], 6
        )
    )
    assert toks == out["token_ids"]


def test_http_endpoint_generates(serve_llm):
    import json
    import urllib.request

    from ray_tpu import serve

    import time

    handle, cfg, params = serve_llm
    body = json.dumps({"prompt_ids": [1, 2, 3], "max_tokens": 4}).encode()
    req = urllib.request.Request(
        "http://127.0.0.1:18931/llm", data=body,
        headers={"Content-Type": "application/json"},
    )
    out = None
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            break
        except Exception:
            time.sleep(0.3)
    assert out is not None, "HTTP endpoint never came up"
    assert out["num_generated"] == 4
    assert len(out["token_ids"]) == 4


def test_batch_inference_processor(ray_start_4_cpus, engine_setup, tmp_path):
    import ray_tpu.data as rdata

    cfg, params = engine_setup
    path = str(tmp_path / "m.npz")
    save_params_npz(params, path)
    llm_cfg = LLMConfig(
        model_config=cfg, checkpoint_path=path,
        max_batch_size=4, max_seq_len=64, accelerator_type="",
    )
    prompts = [[i + 1, i + 2, i + 3] for i in range(8)]
    ds = rdata.from_items([{"prompt_ids": np.array(p)} for p in prompts])
    processor = build_llm_processor(
        llm_cfg, concurrency=1, batch_size=4, max_tokens=5
    )
    out = processor(ds).materialize()
    rows = list(out.iter_rows())
    assert len(rows) == 8
    local = LlamaEngine(cfg, params, max_batch=1, max_seq=64)
    for row in rows[:2]:
        p = [int(x) for x in row["prompt_ids"]]
        got = [int(t) for t in row["generated_ids"][: row["num_generated"]]]
        assert got == local.generate(p, max_tokens=5)
