"""Multi-reactor hub (hub_shards.py): soak + cross-shard semantics.

Tier-1 coverage for the RAY_TPU_HUB_SHARDS>1 control plane:

- a 1k-client connect/submit soak (bounded < 60s): every client's reply
  arrives intact (no dropped frames, no cross-wired replies), every
  task dispatches exactly once (no duplicate dispatch), and the session
  shuts down cleanly with shards running;
- pubsub published through one shard is delivered to subscribers owned
  by other shards;
- a named actor created through one connection is looked up and called
  through another (cross-shard actor routing);
- a registering client's disconnect prunes the fairsched job/tenant
  registries exactly once;
- fairsched priority and quota ordering hold with shards>1 (the
  dispatch policy runs inside the scheduler state service, so ordering
  must be identical no matter which shard a submit arrived on).
"""

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

import ray_tpu
from ray_tpu._private import protocol as P
from ray_tpu._private.client import CoreClient, connect_hub
from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.serialization import (
    dumps_frame,
    dumps_inline,
    loads_frame,
    loads_inline,
    loads_oob,
)

N_SOAK_CLIENTS = 1000
SOAK_CONCURRENCY = 32


@pytest.fixture
def sharded_ray(monkeypatch):
    """A live session with a 4-shard control plane."""
    monkeypatch.setenv("RAY_TPU_HUB_SHARDS", "4")
    ray_tpu.init(num_cpus=4, num_tpus=0, max_workers=4,
                 ignore_reinit_error=True)
    from ray_tpu._private import worker

    assert worker._hub is not None and worker._hub.n_shards == 4
    yield worker._hub
    ray_tpu.shutdown()


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def _decode_inline(payload):
    header, bufs = loads_inline(payload)
    return loads_oob(header, bufs)


def _metric_value(name):
    for m in _client().list_state("metrics"):
        if m["name"] == name and not m["tags"]:
            return m["value"]
    return 0.0


# ------------------------------------------------------- id entropy pool


def test_pooled_id_generation_unique_across_threads():
    """IDs draw from a per-thread batched urandom pool (one syscall per
    1024 ids — the submit hot path shares the driver's GIL with the hub
    thread). Uniqueness and shape must survive pool refills and
    concurrent generators."""
    from ray_tpu._private.ids import _ID_LEN, ObjectID, TaskID

    out = []
    lock = threading.Lock()

    def gen(n):
        local = [ObjectID.generate().binary() for _ in range(n)]
        local += [TaskID.generate().binary() for _ in range(n)]
        with lock:
            out.extend(local)

    threads = [threading.Thread(target=gen, args=(1500,)) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(len(b) == _ID_LEN for b in out)
    assert len(set(out)) == len(out)  # 12k ids, several refills, no dupes


# ------------------------------------------------------------------- soak


def _soak_one(hub_addr, fn_id, idx, deadline):
    """One raw protocol client: connect -> hello -> submit -> get ->
    verify -> close. Speaking the wire directly (no CoreClient reader/
    flusher threads) keeps 1k clients affordable in one test process."""
    conn = connect_hub(hub_addr)
    try:
        conn.send_bytes(dumps_frame((P.HELLO, {
            "role": "client", "worker_id": f"soak-{idx}",
            "pid": os.getpid(), "node_id": "node0",
        })))
        tid = TaskID.generate().binary()
        rid = ObjectID.generate().binary()
        conn.send_bytes(dumps_frame((P.SUBMIT_TASK, {
            "task_id": tid,
            "fn_id": fn_id,
            "args_kind": "inline",
            "args_payload": dumps_inline(((idx,), {})),
            "arg_deps": [],
            "return_ids": [rid],
            "resources": {"CPU": 1.0},
            "options": {"max_retries": 0},
        })))
        conn.send_bytes(dumps_frame((P.GET, {
            "req_id": 1, "object_ids": [rid],
        })))
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not conn.poll(remaining):
                raise TimeoutError(f"soak client {idx}: no reply")
            msg_type, payload = loads_frame(conn.recv_bytes())
            frames = payload if msg_type == "batch" else [(msg_type, payload)]
            for mt, pl in frames:
                if mt == P.REPLY and pl.get("req_id") == 1:
                    (oid, kind, val_payload), = pl["values"]
                    assert oid == rid, "cross-wired reply"
                    assert kind == P.VAL_INLINE, kind
                    return _decode_inline(val_payload)
    finally:
        conn.close()


def test_soak_1k_clients_connect_submit(sharded_ray):
    hub = sharded_ray

    @ray_tpu.remote(num_cpus=1)
    def triple(x):
        return x * 3

    # warm pool + export the function before the storm
    assert ray_tpu.get([triple.remote(i) for i in range(8)], timeout=60) == [
        3 * i for i in range(8)
    ]
    fn_id = triple._fn_id
    assert fn_id

    placed_before = _metric_value("ray_tpu_scheduler_tasks_placed_total")
    events_seq0 = max(
        (e["seq"] for e in _client().list_state("events")), default=-1
    )

    t0 = time.monotonic()
    deadline = t0 + 50.0
    results = {}
    with ThreadPoolExecutor(max_workers=SOAK_CONCURRENCY) as pool:
        futs = {
            pool.submit(_soak_one, hub.addr, fn_id, i, deadline): i
            for i in range(N_SOAK_CLIENTS)
        }
        for fut, i in futs.items():
            results[i] = fut.result(timeout=60)
    elapsed = time.monotonic() - t0
    assert elapsed < 60.0, f"soak took {elapsed:.1f}s"

    # no dropped frames / no cross-wiring: every client saw ITS result
    bad = {i: v for i, v in results.items() if v != 3 * i}
    assert not bad, f"{len(bad)} wrong results, e.g. {list(bad.items())[:3]}"

    # no duplicate dispatch: exactly one placement per task, no retries
    placed_after = _metric_value("ray_tpu_scheduler_tasks_placed_total")
    assert placed_after - placed_before == N_SOAK_CLIENTS
    retries = [
        e for e in _client().list_state("events")
        if e["seq"] > events_seq0 and e["kind"] == "task_retry"
    ]
    assert retries == []

    # the load actually spread: every reactor shard owned client traffic
    shard_rows = [
        r for r in _client().list_state("shards") if "shard" in r
    ]
    assert len(shard_rows) == 4
    assert all(r["frames_sent"] > 0 for r in shard_rows), shard_rows
    svc_rows = {
        r["service"]: r["processed"]
        for r in _client().list_state("shards") if "service" in r
    }
    assert svc_rows.get("scheduler", 0) >= N_SOAK_CLIENTS  # hellos+submits
    assert svc_rows.get("objects", 0) >= N_SOAK_CLIENTS    # gets

    # clean shutdown with shards>1 (the fixture's shutdown also runs;
    # this asserts it completes rather than abandoning the state plane)
    ray_tpu.shutdown()
    assert hub._shutdown_evt.wait(10)
    for s in hub._shards:
        s.join(timeout=5)
        assert not s.is_alive()


# ------------------------------------------------------------ cross-shard


def test_pubsub_crosses_shards(sharded_ray, tmp_path):
    """Round-robin accept lands consecutive client connections on
    different shards; full-mesh pubsub then proves publishes fan out
    across the shard boundary (every subscriber hears every
    publisher, wherever each socket lives)."""
    clients = []
    try:
        for i in range(4):
            cl = CoreClient(
                sharded_ray.addr, str(tmp_path / f"sub{i}"),
                role="client", worker_id=f"sub-{i}",
            )
            cl.inline_only = True
            clients.append(cl)
        heard = {i: [] for i in range(4)}
        evts = {i: threading.Event() for i in range(4)}
        for i, cl in enumerate(clients):
            def cb(data, i=i):
                heard[i].append(data)
                if len(heard[i]) >= 4:
                    evts[i].set()
            cl.subscribe("fanout", cb)
        time.sleep(0.3)  # subscriptions settle on the state plane
        for i, cl in enumerate(clients):
            cl.publish("fanout", f"from-{i}")
            cl.flush()
        for i in range(4):
            assert evts[i].wait(20), f"subscriber {i} heard {heard[i]}"
            assert sorted(heard[i]) == [f"from-{j}" for j in range(4)]
    finally:
        for cl in clients:
            cl.close()


def test_named_actor_lookup_and_call_across_shards(sharded_ray, tmp_path):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self, k):
            self.n += k
            return self.n

    handle = Counter.options(name="shard-counter").remote()
    assert ray_tpu.get(handle.bump.remote(1), timeout=60) == 1

    # a SECOND connection (different shard, round-robin) resolves the
    # name and calls the same actor instance
    cl2 = CoreClient(
        sharded_ray.addr, str(tmp_path / "cl2"),
        role="client", worker_id="cross-shard-caller",
    )
    cl2.inline_only = True
    try:
        aid = cl2.get_named_actor("shard-counter")
        assert aid is not None
        refs = cl2.submit_actor_task(
            ActorID(aid), "bump", "inline",
            dumps_inline(((10,), {})), [], 1, {},
        )
        (val,) = cl2.get(refs)
        assert val == 11  # same instance: 1 (driver) + 10 (cross-shard)
    finally:
        cl2.close()
    # and the driver still shares state with it
    assert ray_tpu.get(handle.bump.remote(1), timeout=60) == 12


def test_disconnect_prunes_fairsched_exactly_once(sharded_ray, tmp_path):
    cl = CoreClient(
        sharded_ray.addr, str(tmp_path / "tenantconn"),
        role="client", worker_id="tenant-client",
    )
    cl.inline_only = True
    cl.register_job("soak-job", tenant="soak-tenant", priority=2)
    jobs = {j["job_id"] for j in _client().list_state("jobs")}
    assert "soak-job" in jobs
    seq0 = max(
        (e["seq"] for e in _client().list_state("events")), default=-1
    )
    cl.close()

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        jobs = {j["job_id"] for j in _client().list_state("jobs")}
        if "soak-job" not in jobs:
            break
        time.sleep(0.1)
    assert "soak-job" not in jobs
    tenants = {t["tenant"] for t in _client().list_state("tenants")}
    assert "soak-tenant" not in tenants
    # exactly once: one client_disconnect event for this close, and the
    # registries did not resurrect afterwards
    time.sleep(0.5)
    disc = [
        e for e in _client().list_state("events")
        if e["seq"] > seq0 and e["kind"] == "client_disconnect"
    ]
    assert len(disc) == 1, disc
    assert "soak-job" not in {
        j["job_id"] for j in _client().list_state("jobs")
    }


def test_shard_fatal_tears_the_session_down(monkeypatch):
    """A dead reactor shard must fail LOUDLY (single-reactor parity):
    the state plane dumps the flight recorder and tears the session
    down rather than leaving a half-alive hub where shard 0's accepts
    (or 1-in-N adoptions) silently blackhole."""
    from ray_tpu._private.hub_shards import SHARD_EVENT

    monkeypatch.setenv("RAY_TPU_HUB_SHARDS", "2")
    ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    from ray_tpu._private import worker

    hub = worker._hub
    try:
        assert hub.n_shards == 2
        # the rings are created on the state-plane thread after start:
        # on a loaded 1-core box the thread may not have run yet
        deadline = time.monotonic() + 10
        while not hub._shard_rings and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hub._shard_rings, "state plane never built its rings"
        # inject the event a dying shard pushes from its except path
        hub._shard_rings[0].push(
            (None, None, SHARD_EVENT, {"kind": "shard_fatal", "shard": 1})
        )
        assert hub._shutdown_evt.wait(15), "state plane did not shut down"
        assert not hub._running
    finally:
        ray_tpu.shutdown()


# ------------------------------------------- fairsched ordering w/ shards


def test_priority_jumps_the_queue_with_shards(sharded_ray):
    """Same invariant as test_fairsched.test_priority_jumps_the_queue,
    but with the 4-shard control plane: fairsched runs inside the
    scheduler state service, so priority ordering must be identical no
    matter which shard carried each submit."""
    # flood all four workers with blockers, then queue lows before
    # highs; one high per worker means every worker must pick a high
    # before any low can start
    @ray_tpu.remote(num_cpus=1)
    def stamp(tag):
        time.sleep(0.05)
        return (tag, time.monotonic())

    ray_tpu.get([stamp.remote(f"warm{i}") for i in range(4)], timeout=60)
    blockers = [stamp.remote(f"blocker{i}") for i in range(4)]
    low = [stamp.options(priority=0).remote(f"low{i}") for i in range(6)]
    high = [stamp.options(priority=7).remote(f"high{i}") for i in range(4)]
    done = dict(ray_tpu.get(low + high + blockers, timeout=60))
    assert max(done[f"high{i}"] for i in range(4)) < min(
        done[f"low{i}"] for i in range(6)
    ), done


def test_quota_parks_then_completes_with_shards(sharded_ray):
    cl = _client()
    cl.register_job("shard-quota-job", tenant="qshard",
                    quota={"CPU": 1.0})

    @ray_tpu.remote(num_cpus=1)
    def slow(i):
        time.sleep(0.1)
        return i

    refs = [slow.options(tenant="qshard").remote(i) for i in range(4)]
    # over-quota work parks at admission (1 CPU cap, 4 submits)
    deadline = time.monotonic() + 20
    saw_parked = False
    while time.monotonic() < deadline and not saw_parked:
        saw_parked = any(
            r.get("pending_quota") for r in _client().list_state("demand")
        ) or _metric_value("ray_tpu_sched_pending_quota") > 0
        if saw_parked:
            break
        time.sleep(0.02)
    out = ray_tpu.get(refs, timeout=120)
    assert out == list(range(4))
    assert saw_parked, "quota admission never parked over-quota work"
    # all charges released once the work drained
    tenants = {
        t["tenant"]: t for t in _client().list_state("tenants")
    }
    admitted = tenants.get("qshard", {}).get("admitted") or {}
    assert all(v == 0 for v in admitted.values()), admitted
