"""CLI (`python -m ray_tpu ...`) — reference: ray start/status/list/job
CLIs (python/ray/scripts/scripts.py, util/state/state_cli.py,
dashboard/modules/job/cli.py)."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.usefixtures("shutdown_only")


@pytest.fixture
def cli_cluster(tmp_path):
    """A head started through the CLI in a subprocess, isolated HOME."""
    env = dict(os.environ)
    env["HOME"] = str(tmp_path)
    env["RAY_TPU_NUM_TPUS"] = "0"
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start", "--head",
         "--num-cpus", "2", "--host", "127.0.0.1"],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    addr_file = tmp_path / ".ray_tpu" / "head_address"
    deadline = time.monotonic() + 30
    while not addr_file.exists():
        assert head.poll() is None, head.stdout.read()
        assert time.monotonic() < deadline, "head never wrote address file"
        time.sleep(0.1)
    yield env, addr_file.read_text().strip(), head
    if head.poll() is None:
        head.send_signal(signal.SIGINT)
        try:
            head.wait(timeout=10)
        except subprocess.TimeoutExpired:
            head.kill()


def _cli(env, *argv, timeout=60):
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", *argv],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    return out.stdout


def test_status_and_list(cli_cluster):
    env, addr, _head = cli_cluster
    out = _cli(env, "status")
    assert "nodes: 1" in out
    assert "CPU" in out
    out = _cli(env, "list", "nodes", "--format", "json")
    nodes = json.loads(out)
    assert len(nodes) == 1 and nodes[0]["alive"]
    out = _cli(env, "list", "actors")
    assert "(none)" in out or "ACTOR_ID" in out


def test_events_and_summary_tasks(cli_cluster):
    env, addr, _head = cli_cluster
    # drive a tiny workload through a job so there are task events
    out = _cli(
        env, "job", "submit", "--wait", "--",
        sys.executable, "-c",
        "import os, ray_tpu; "
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS']); "
        "f = ray_tpu.remote(lambda: 1); "
        "print(sum(ray_tpu.get([f.remote() for _ in range(3)])))",
        timeout=120,
    )
    assert "SUCCEEDED" in out

    out = _cli(env, "summary", "tasks")
    summary = json.loads(out)
    assert summary["total"] >= 1
    assert "by_state" in summary
    # the lifecycle breakdown rides the same summary
    assert "queue_wait_s" in summary and "run_time_s" in summary
    if summary["run_time_s"]:
        assert {"p50", "p95", "p99"} <= set(summary["run_time_s"])

    out = _cli(env, "events", "--format", "json")
    events = json.loads(out)
    assert isinstance(events, list) and events
    assert all("kind" in e and "seq" in e for e in events)
    assert any(e["kind"] == "hub_start" for e in events)
    # table mode renders without blowing up, and the filter narrows
    out = _cli(env, "events")
    assert "KIND" in out
    out = _cli(env, "events", "--kind", "hub_start", "--format", "json")
    assert all(e["kind"] == "hub_start" for e in json.loads(out))


def test_job_submit_wait_logs(cli_cluster):
    env, addr, _head = cli_cluster
    out = _cli(
        env, "job", "submit", "--wait", "--",
        sys.executable, "-c", "print('hello from job')",
        timeout=120,
    )
    assert "SUCCEEDED" in out
    assert "hello from job" in out


def test_summary_and_timeline(cli_cluster, tmp_path):
    env, addr, _head = cli_cluster
    out = _cli(env, "summary", "tasks")
    json.loads(out)
    tl = tmp_path / "tl.json"
    out = _cli(env, "timeline", "--output", str(tl))
    assert tl.exists()
    json.loads(tl.read_text())


def test_stop_halts_head(cli_cluster):
    env, addr, head = cli_cluster
    _cli(env, "stop")
    head.wait(timeout=15)
    assert head.poll() is not None


def test_node_joins_via_cli(cli_cluster):
    """`python -m ray_tpu start --address tcp://...` turns this process
    into a node agent that registers with the head (reference:
    ray start --address)."""
    env, addr, _head = cli_cluster
    joiner = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu", "start",
         "--address", addr, "--num-cpus", "1", "--node-id", "clinode"],
        env={**env, "RAY_TPU_NUM_TPUS": "0"},
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            out = _cli(env, "list", "nodes", "--format", "json")
            nodes = json.loads(out)
            if any(n["node_id"] == "clinode" and n["alive"] for n in nodes):
                break
            assert joiner.poll() is None, joiner.stdout.read()
            time.sleep(0.3)
        else:
            raise AssertionError(f"cli node never registered: {nodes}")
        out = _cli(env, "status")
        assert "clinode" in out
    finally:
        joiner.terminate()
        try:
            joiner.wait(timeout=10)
        except subprocess.TimeoutExpired:
            joiner.kill()
