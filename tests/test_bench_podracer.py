"""bench_podracer.py harness smoke test (tier-1 safe, not marked slow).

Mirrors tests/test_bench_harness.py for the Podracer rows: one --smoke
micro-iteration end to end, asserting the --json report covers every
BASELINES metric with the platform-stamp/ratio-refusal contract —
numbers are NOT checked (smoke counts are sized for latency).
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench_podracer.py")


def test_smoke_run_reports_every_baseline_metric(tmp_path):
    out_path = tmp_path / "bench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT)
    r = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--trials", "2",
         "--json", str(out_path)],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=420,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"

    data = json.loads(out_path.read_text())
    assert data["mode"] == "smoke"
    assert data["trials"] == 2

    sys.path.insert(0, REPO_ROOT)
    try:
        from bench_podracer import BASELINE_PLATFORM, BASELINES
    finally:
        sys.path.remove(REPO_ROOT)

    missing = set(BASELINES) - set(data["metrics"])
    assert not missing, f"BASELINES metrics missing from report: {missing}"

    assert data["platform"] == BASELINE_PLATFORM  # JAX_PLATFORMS=cpu above
    for name, rec in data["metrics"].items():
        assert rec.get("platform"), f"{name} row missing platform stamp"
        if rec["platform"] != BASELINE_PLATFORM:
            assert rec["vs_baseline"] is None, name
        elif name in BASELINES:
            assert rec["vs_baseline"] is not None, name
        assert rec["value"] > 0, f"{name} reported a non-positive value"
        trials = rec.get("trials")
        assert trials is not None and len(trials) == 2, name
        assert (
            min(trials) - 0.01 <= rec["value"] <= max(trials) + 0.01
        ), (name, rec["value"], trials)

    # every stdout metric line is one JSON object (the scrapeable form)
    parsed = [
        json.loads(line) for line in r.stdout.splitlines()
        if line.startswith("{")
    ]
    assert {p["metric"] for p in parsed} >= set(BASELINES)


def test_report_refuses_cross_platform_ratio(monkeypatch):
    """A Podracer row measured on non-baseline hardware keeps its
    platform stamp and has vs_baseline refused — cpu-box steps/s are
    not comparable to MULTICHIP numbers (bench_podracer docstring)."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench_podracer
    finally:
        sys.path.remove(REPO_ROOT)

    monkeypatch.setattr(bench_podracer, "RESULTS", [])
    monkeypatch.setattr(bench_podracer, "_detect_platform", lambda: "tpu")
    bench_podracer.report("anakin_steps_per_sec", 12345.0, "steps/s")
    rec = bench_podracer.RESULTS[-1]
    assert rec["platform"] == "tpu"
    assert rec["vs_baseline"] is None

    monkeypatch.setattr(
        bench_podracer, "_detect_platform",
        lambda: bench_podracer.BASELINE_PLATFORM,
    )
    bench_podracer.report("sebulba_steps_per_sec", 12345.0, "steps/s")
    rec = bench_podracer.RESULTS[-1]
    assert rec["platform"] == bench_podracer.BASELINE_PLATFORM
    assert rec["vs_baseline"] is not None
