"""Multi-host training: a JaxTrainer gang spanning two simulated hosts
runs a REAL jax.distributed rendezvous (coordinator on rank 0, CPU
backend) and a cross-process collective — the reference's
dist.init_process_group rendezvous path (train/torch/config.py:66-124)
exercised end-to-end over the multi-process runtime."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def two_host_cluster():
    # head has only 1 CPU: a 2x1-CPU gang cannot fit on one host, so the
    # PACK placement group must span hosts
    c = Cluster(head_num_cpus=1)
    c.add_node(num_cpus=1)
    yield c
    c.shutdown()


def _train_fn(config):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu import train
    from ray_tpu.train import session

    ctx = session.get_context()
    # the backend ran jax.distributed.initialize before train_fn started
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    # cross-process collective over DCN: allgather each process's rank
    from jax.experimental import multihost_utils

    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.array([float(rank)]))
    ).reshape(-1)
    session.report(
        {
            "rank_sum": float(gathered.sum()),
            "n_processes": jax.process_count(),
            "world_rank": ctx.get_world_rank(),
            "node_rank": ctx.get_node_rank(),
        }
    )


def test_jax_distributed_gang_spans_hosts(two_host_cluster):
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.backend import JaxConfig
    from ray_tpu.train.jax_trainer import JaxTrainer

    trainer = JaxTrainer(
        train_loop_per_worker=_train_fn,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}
        ),
        jax_config=JaxConfig(enable_distributed=True),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["n_processes"] == 2
    # ranks 0..1 allgathered on every process: sum == 1
    assert result.metrics["rank_sum"] == 1.0


def test_gang_actually_spans_two_hosts(two_host_cluster):
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.jax_trainer import JaxTrainer

    seen = []

    def spy_fn(config):
        import os

        from ray_tpu.train import session

        session.report({"node": os.environ.get("RAY_TPU_NODE_ID", "node0")})

    trainer = JaxTrainer(
        train_loop_per_worker=spy_fn,
        scaling_config=ScalingConfig(
            num_workers=2, resources_per_worker={"CPU": 1}
        ),
    )
    result = trainer.fit()
    assert result.error is None, result.error
