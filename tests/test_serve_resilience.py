"""Serve resilience (PR 15): admission control / load shedding,
deadline propagation, replica health ejection, and the drain-vs-shed
accounting fix.

Tier-1 coverage:
  * past max_queued_requests, .remote() sheds synchronously with a
    retriable RequestShedError; admitted requests still complete, and
    shed/requests counters stay disjoint in summarize_serve
  * a request deadline (handle.options(request_timeout_s=...)) bounds
    result() — no parking on a literal 60 s wait
  * the deadline rides request_meta into @serve.batch: an expired
    member is dropped pre-execute (RequestExpiredError on its future)
    WITHOUT poisoning the rest of the batch
  * consecutive failures eject a replica from the routing candidate
    set; success resets the streak; the transparent retry makes a
    replica death invisible to callers
  * the HTTP proxy maps shed -> 503 (+ Retry-After) and expired -> 504,
    honoring the X-Request-Timeout-S per-request override
  * drain_accounting books drained/dropped per victim (regression: the
    old aggregate-sum double-counted when load moved between victims)
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.exceptions import (
    GetTimeoutError,
    RequestExpiredError,
    RequestShedError,
)


@pytest.fixture
def serve_cleanup(ray_start_4_cpus):
    yield
    serve.shutdown()


@pytest.fixture
def serve_config():
    """Save/restore the serve resilience config knobs a test overrides."""
    from ray_tpu._private.config import RAY_TPU_CONFIG

    keys = (
        "serve_request_timeout_s", "serve_max_queued_requests",
        "serve_ejection_failures", "serve_retry_attempts",
        "serve_retry_base_s",
    )
    saved = {k: RAY_TPU_CONFIG.get(k) for k in keys}
    yield RAY_TPU_CONFIG
    for k, v in saved.items():
        RAY_TPU_CONFIG.set(k, v)


# ----------------------------------------------------- drain accounting


def test_drain_accounting_books_per_victim():
    """Regression for the aggregate-sum double-count: drained and
    dropped must be booked per victim so load moving BETWEEN victims
    during the grace window can't inflate (or deflate) either counter."""
    from ray_tpu.serve._private.controller import drain_accounting

    # clean drain: everything in-flight finished before the deadline
    assert drain_accounting([5, 3], [0, 0]) == (8, 0)
    # nothing drained: all of it was still running at the kill
    assert drain_accounting([4, 2], [4, 2]) == (0, 6)
    # mixed: one victim drained fully, the other kept 2 -> dropped
    assert drain_accounting([5, 3], [0, 2]) == (6, 2)
    # load GREW on one victim during the window (requests still routed
    # to it): the gain is not "drained" — per-victim max(0, i-f) clamps
    # it, and the final load books as dropped
    assert drain_accounting([4, 0], [0, 2]) == (4, 2)
    # disjointness invariant: drained + dropped never exceeds
    # initial + arrivals, and both are non-negative
    assert drain_accounting([], []) == (0, 0)


# -------------------------------------------------- admission / shedding


def test_shed_past_queue_cap(serve_cleanup, serve_config):
    @serve.deployment(max_queued_requests=2)
    class Slow:
        def __call__(self, x):
            time.sleep(1.0)
            return x

    handle = serve.run(Slow.bind())
    # learn the cap (first request also warms the routing table)
    assert handle.remote(0).result() == 0
    admitted, shed = [], []
    for i in range(6):
        try:
            admitted.append(handle.remote(i))
        except RequestShedError as e:
            shed.append(e)
    assert shed, "no request was shed past the cap"
    assert len(admitted) <= 2
    first = shed[0]
    assert first.deployment == "Slow"
    assert first.cap == 2 and first.queued >= 2
    # admitted requests are unaffected by the shedding around them
    assert [r.result() for r in admitted] == list(range(len(admitted)))
    # shed is disjoint from routed-request accounting: only admitted
    # requests count as requests; shed rides its own counter
    from ray_tpu.util import state as state_api

    deadline = time.time() + 15
    dep = None
    while time.time() < deadline:
        dep = state_api.summarize_serve()["deployments"].get("Slow")
        if dep and dep.get("shed", 0) >= len(shed):
            break
        time.sleep(0.2)
    assert dep is not None
    assert dep["shed"] >= len(shed)
    assert dep["requests"] == 1 + len(admitted)
    assert dep["dropped"] == 0 and dep["drained"] == 0


# ------------------------------------------------- deadline propagation


def test_request_deadline_bounds_result(serve_cleanup, serve_config):
    @serve.deployment
    class Sleepy:
        def __call__(self, s):
            time.sleep(s)
            return "done"

    handle = serve.run(Sleepy.bind())
    assert handle.remote(0).result() == "done"
    t0 = time.monotonic()
    resp = handle.options(request_timeout_s=0.4).remote(5.0)
    with pytest.raises(GetTimeoutError):
        resp.result()
    elapsed = time.monotonic() - t0
    assert elapsed < 3.0, f"deadline did not bound the wait: {elapsed:.1f}s"
    # an undeadlined sibling call on the same handle still works
    assert handle.remote(0).result() == "done"


def test_batch_member_deadline_drops_without_poisoning(
    serve_cleanup, serve_config
):
    """Satellite: deadline propagation through @serve.batch. Member A's
    deadline expires while it parks waiting for the batch to fill;
    when B arrives and the batch fires, A is dropped pre-execute (the
    user callable never sees its item) and B completes normally."""

    @serve.deployment(max_ongoing_requests=8)
    class Batched:
        @serve.batch(max_batch_size=2, batch_wait_timeout_s=10.0)
        async def __call__(self, items):
            got = list(items)
            return [{"saw": got} for _ in items]

    handle = serve.run(Batched.bind())
    resp_a = handle.options(request_timeout_s=0.5).remote("a")
    time.sleep(1.5)  # past A's deadline; batch still waiting (size 1/2)
    resp_b = handle.options(request_timeout_s=30.0).remote("b")
    # B's batch executed WITHOUT the expired member
    assert resp_b.result() == {"saw": ["b"]}
    # A surfaces as a deadline failure (client-side timeout or the
    # replica-side pre-execute drop, whichever wins the race)
    with pytest.raises((GetTimeoutError, RequestExpiredError)):
        resp_a.result()


# ------------------------------------------------------ health ejection


class _FakeActorId:
    def __init__(self, b):
        self._b = b

    def binary(self):
        return self._b


class _FakeReplica:
    def __init__(self, b):
        self._actor_id = _FakeActorId(b)


def test_ejection_streaks_unit(serve_config, monkeypatch):
    """Router-side ejection bookkeeping, no cluster: a replica leaves
    the candidate set after N consecutive failures; one success resets
    its streak; options() views share the ejected set."""
    from ray_tpu.serve.handle import DeploymentHandle

    serve_config.set("serve_ejection_failures", 3)
    monkeypatch.setattr(
        DeploymentHandle, "_ensure_prober", lambda self: None
    )
    h = DeploymentHandle("D")
    r1, r2 = _FakeReplica(b"r1"), _FakeReplica(b"r2")
    h._replicas = [r1, r2]
    h._note_failure(b"r1")
    h._note_failure(b"r1")
    assert not h._ejected  # below threshold
    h._note_success(b"r1")  # success resets the streak
    h._note_failure(b"r1")
    h._note_failure(b"r1")
    assert not h._ejected
    h._note_failure(b"r1")  # third consecutive -> ejected
    assert set(h._ejected) == {b"r1"}
    assert h._ejected[b"r1"] is r1
    # an options() view shares ejection state — it must not resurrect r1
    view = h.options(method_name="other")
    assert set(view._ejected) == {b"r1"}
    # a replica unknown to the candidate set can't be ejected
    h._note_failure(b"zz")
    h._note_failure(b"zz")
    h._note_failure(b"zz")
    assert b"zz" not in h._ejected


def test_replica_death_is_transparent(serve_cleanup, serve_config):
    """Killing a replica mid-service stays invisible to callers: the
    bounded transparent retry re-routes onto the survivor (ejection
    threshold 1 pulls the corpse from the candidate set immediately)."""
    serve_config.set("serve_ejection_failures", 1)

    @serve.deployment(num_replicas=2)
    class W:
        def __call__(self, _):
            return os.getpid()

    handle = serve.run(W.bind())
    pids = {handle.remote(None).result() for _ in range(12)}
    assert len(pids) == 2
    from ray_tpu.serve._private.controller import CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    info = ray_tpu.get(ctrl.get_routing_info.remote("W"))
    ray_tpu.kill(info["replicas"][0])
    # every request still succeeds; no caller sees ActorDiedError
    results = [handle.remote(None).result() for _ in range(12)]
    assert all(isinstance(p, int) for p in results)


# ------------------------------------------------------ proxy mapping


def _urlopen_status(url, headers=None, timeout=10):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers)


def test_http_maps_expired_to_504_and_shed_to_503(
    serve_cleanup, serve_config
):
    @serve.deployment(max_queued_requests=1)
    class Pokey:
        def __call__(self, req):
            time.sleep(2.0)
            return "ok"

    serve.run(Pokey.bind(), route_prefix="/pokey",
              http_options={"port": 18769})
    base = "http://127.0.0.1:18769/pokey"
    # wait for the proxy route table
    deadline = time.time() + 15
    status = None
    while time.time() < deadline:
        status, _ = _urlopen_status(base, timeout=10)
        if status != 404:
            break
        time.sleep(0.3)
    assert status == 200
    # per-request deadline override via header -> 504 well before the
    # 2 s execute (and far before any 60 s default)
    t0 = time.monotonic()
    status, _ = _urlopen_status(
        base, headers={"X-Request-Timeout-S": "0.3"}, timeout=10
    )
    assert status == 504
    assert time.monotonic() - t0 < 1.9
    # saturate the cap from background threads, then overflow -> 503
    import threading

    hold = [
        threading.Thread(target=_urlopen_status, args=(base,),
                         kwargs={"timeout": 30})
        for _ in range(2)
    ]
    for t in hold:
        t.start()
    time.sleep(0.4)  # let the holders reach the replica
    statuses = []
    hdrs = []
    for _ in range(4):
        s, h = _urlopen_status(base, timeout=10)
        statuses.append(s)
        hdrs.append(h)
    for t in hold:
        t.join()
    assert 503 in statuses, statuses
    shed_headers = hdrs[statuses.index(503)]
    assert shed_headers.get("Retry-After") == "1"
