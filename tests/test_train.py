"""Train library tests.

Pattern from the reference: train against small CPU worker groups
(python/ray/train/tests/test_data_parallel_trainer.py,
test_backend.py) — real actors, tiny models, checkpoint/restore and
failure-path assertions.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "results")


def test_single_worker_report(ray_start_4_cpus, storage):
    def loop(config):
        for i in range(3):
            train.report({"loss": 1.0 / (i + 1), "step": i})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["training_iteration"] == 3


def test_context_ranks(ray_start_4_cpus, storage):
    def loop(config):
        ctx = train.get_context()
        train.report({"rank": ctx.get_world_rank(), "world": ctx.get_world_size()})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 2
    assert result.metrics["rank"] == 0  # controller surfaces rank-0 metrics


def test_checkpoint_roundtrip(ray_start_4_cpus, storage):
    def loop(config):
        ckpt = Checkpoint.from_state({"weights": [1.0, 2.0], "step": 7})
        train.report({"loss": 0.5}, checkpoint=ckpt)

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    state = result.checkpoint.to_state()
    assert state["step"] == 7


def test_top_k_retention(ray_start_4_cpus, storage):
    def loop(config):
        for i in range(5):
            ckpt = Checkpoint.from_state({"i": i})
            train.report({"score": float(i % 3)}, checkpoint=ckpt)

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t4",
            storage_path=storage,
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"
            ),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    kept = sorted(os.listdir(os.path.join(storage, "t4")))
    assert len(kept) == 2
    # latest checkpoint must survive even if low-scoring
    assert "checkpoint_000004" in kept


def test_failure_restart_resumes_from_checkpoint(ray_start_4_cpus, storage):
    marker = os.path.join(storage, "poison")
    os.makedirs(storage, exist_ok=True)

    def loop(config):
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            start = ckpt.to_state()["step"] + 1
        for i in range(start, 4):
            if i == 2 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("boom at step 2")
            train.report(
                {"step": i, "resumed_from": start},
                checkpoint=Checkpoint.from_state({"step": i}),
            )

    trainer = DataParallelTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t5",
            storage_path=storage,
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3
    assert result.metrics["resumed_from"] == 2  # resumed, not restarted


def test_failure_exhausted(ray_start_4_cpus, storage):
    def loop(config):
        raise ValueError("always broken")

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t6",
            storage_path=storage,
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always broken" in str(result.error)


def test_jax_trainer_mesh_training(ray_start_4_cpus, storage):
    """End-to-end: JaxTrainer worker builds a mesh over the virtual CPU
    devices and runs a pjit data-parallel step (the §7.3 minimum slice)."""

    def loop(config):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.parallel import make_mesh

        mesh = make_mesh()  # all 8 virtual devices on the fsdp axis
        w = jnp.zeros((4,))
        xs = jnp.ones((8, 4))
        ys = jnp.full((8,), 3.0)

        @jax.jit
        def step(w, x, y):
            def loss(w):
                return jnp.mean((x @ w - y) ** 2)

            l, g = jax.value_and_grad(loss)(w)
            return w - 0.1 * g, l

        with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else mesh:
            for i in range(10):
                w, l = step(w, xs, ys)
        train.report({"loss": float(l)})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t7", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 1.0


def test_dataset_shard_passthrough(ray_start_4_cpus, storage):
    class FakeDataset:
        def __init__(self, items):
            self.items = items

        def split(self, n):
            return [FakeDataset(self.items[i::n]) for i in range(n)]

    def loop(config):
        shard = train.get_dataset_shard("train")
        train.report({"n": len(shard.items)})

    trainer = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t8", storage_path=storage),
        datasets={"train": FakeDataset(list(range(10)))},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["n"] == 5


def test_elastic_resize_on_unschedulable_gang(ray_start_4_cpus, tmp_path):
    """Elastic training (reference: train/v2 ScalingPolicy): a gang that
    cannot be placed at full size restarts at a smaller size bounded by
    min_workers instead of failing."""
    from ray_tpu.train import RunConfig
    from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
    from ray_tpu.air.config import FailureConfig, ScalingConfig

    def loop(config):
        from ray_tpu.train import session

        session.report({"world": session.get_context().get_world_size()})

    trainer = DataParallelTrainer(
        loop,
        # 8 x 1-CPU workers can never fit on 4 CPUs: must shrink 8->4
        scaling_config=ScalingConfig(
            num_workers=8,
            resources_per_worker={"CPU": 1},
            min_workers=2,
            placement_timeout_s=2.0,
        ),
        run_config=RunConfig(
            name="elastic", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=3),
        ),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["world"] == 4  # halved once: 8 -> 4 fits


def test_torch_trainer_ddp_gloo(ray_start_4_cpus):
    """TorchTrainer gang: gloo process group over framework rendezvous,
    DDP gradient averaging across 2 worker processes (reference:
    train/torch/config.py _TorchBackend + tests/test_backend.py)."""
    from ray_tpu import train
    from ray_tpu.air.config import ScalingConfig

    def loop(config):
        import numpy as np
        import torch
        import torch.distributed as dist

        from ray_tpu.train.torch_trainer import prepare_model

        ctx = train.get_context()
        assert dist.is_initialized()
        assert dist.get_world_size() == 2
        assert dist.get_rank() == ctx.get_world_rank()

        # allreduce sanity
        t = torch.tensor([float(ctx.get_world_rank() + 1)])
        dist.all_reduce(t)
        assert t.item() == 3.0  # 1 + 2

        # DDP: per-rank different data -> identical averaged grads
        torch.manual_seed(0)
        model = prepare_model(torch.nn.Linear(4, 1))
        x = torch.full((8, 4), float(ctx.get_world_rank()))
        loss = model(x).sum()
        loss.backward()
        g = model.module.weight.grad.numpy().copy()
        train.report({"grad0": float(g[0][0])})

    trainer = train.TorchTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)
    )
    result = trainer.fit()
    # DDP averages grads: ranks saw x=0 and x=1 -> mean grad = 8*(0+1)/2
    assert abs(result.metrics["grad0"] - 4.0) < 1e-6
