"""Serve-plane observability (PR 13): request spans through
proxy/router/replica/batching, per-deployment/per-route SLO metrics,
and graceful drain-before-kill teardown.

Tier-1 coverage:
  * a traced HTTP request stitches >= 6 serve.* spans (plus the
    task-layer spans of the underlying actor call) across the
    proxy/driver/replica processes with correct parentage
  * analyze_trace partitions the trace EXACTLY (stages + untracked =
    end-to-end) and names a dominant stage
  * latency percentiles + request counts land in summarize_serve after
    N requests; batch efficiency reflects a forced partial batch
  * sampling 0 (default) emits no spans at all
  * redeploy mid-request drains the in-flight request (counted drained,
    nothing dropped) instead of killing the replica under it
"""

import time

import pytest

import ray_tpu
from ray_tpu import serve

SERVE_SPAN_NAMES = {
    "serve.proxy_recv",
    "serve.route",
    "serve.queue_wait",
    "serve.batch_wait",
    "serve.execute",
    "serve.response_return",
}


@pytest.fixture
def traced_serve(monkeypatch):
    """Cluster with runtime tracing head-sampled at 1.0 (env must be
    set before init: clients read it at construction and spawned
    workers inherit it), torn down serve-first."""
    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture
def serve_ray(ray_start_4_cpus):
    yield
    serve.shutdown()


def _client():
    from ray_tpu._private import worker

    return worker.get_client()


def _find_serve_trace(deadline_s=20.0):
    """Poll the hub trace store for the trace carrying the serve span
    chain (span records ride async send batches of three processes)."""
    client = _client()
    deadline = time.monotonic() + deadline_s
    best = []
    while time.monotonic() < deadline:
        for row in client.list_state("traces"):
            spans = client.list_state("traces", trace_id=row["trace_id"])
            names = {s["name"] for s in spans}
            if SERVE_SPAN_NAMES <= names:
                return spans
            if len(names & SERVE_SPAN_NAMES) > len(
                {s["name"] for s in best} & SERVE_SPAN_NAMES
            ):
                best = spans
        time.sleep(0.1)
    raise AssertionError(
        "no trace carried the full serve span chain; best candidate "
        f"had: {sorted({s['name'] for s in best})}"
    )


def _one(spans, name):
    found = [s for s in spans if s["name"] == name]
    assert len(found) == 1, (name, [s["name"] for s in spans])
    return found[0]


def test_traced_http_request_full_span_chain(traced_serve):
    """One HTTP request -> >= 6 stitched serve spans over >= 3
    processes, parentage proxy_recv -> route -> (actor submit) ->
    execute -> batch_wait, and an EXACT stage partition."""
    from ray_tpu.util.tracing import analyze_trace

    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.02)
        async def handler(self, items):
            return [len(items)] * len(items)

        async def __call__(self, request):
            return await self.handler(request)

    serve.run(Batched.bind(), route_prefix="/obs",
              http_options={"port": 18841})

    import urllib.request

    deadline = time.time() + 15
    status = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                "http://127.0.0.1:18841/obs", timeout=5
            ) as r:
                status = r.status
            break
        except Exception:
            time.sleep(0.3)
    assert status == 200

    spans = _find_serve_trace()
    names = {s["name"] for s in spans}
    assert SERVE_SPAN_NAMES <= names
    serve_spans = [s for s in spans if s["name"].startswith("serve.")]
    assert len(serve_spans) >= 6
    # three distinct processes: proxy actor, driver-side router thread
    # lives in the proxy process, replica worker, plus the hub spans
    assert len({(s.get("node_id"), s.get("pid")) for s in spans}) >= 3

    proxy = _one(spans, "serve.proxy_recv")
    route = _one(spans, "serve.route")
    execute = _one(spans, "serve.execute")
    batch_wait = _one(spans, "serve.batch_wait")
    queue_wait = _one(spans, "serve.queue_wait")
    ret = _one(spans, "serve.response_return")
    assert proxy["parent_id"] is None  # the ingress is the trace root
    assert route["parent_id"] == proxy["span_id"]
    assert ret["parent_id"] == proxy["span_id"]
    # the task-layer actor submit parents under serve.route (the
    # ambient context pushed around handle_request.remote)
    submits = [
        s for s in spans
        if s["name"] == "client.submit_actor"
        and s["parent_id"] == route["span_id"]
    ]
    assert submits, [(s["name"], s["parent_id"]) for s in spans]
    # replica-side spans parent under the worker execute span, and
    # batch_wait nests inside THIS request's serve.execute
    assert batch_wait["parent_id"] == execute["span_id"]
    assert queue_wait["parent_id"] == execute["parent_id"]
    assert batch_wait["attrs"]["batch_size"] == "1"
    assert batch_wait["attrs"]["max_batch_size"] == "4"

    # exact partition: per-stage durations + untracked == end-to-end
    a = analyze_trace(spans)
    stage_sum = sum(v["dur_s"] for v in a["stages"].values())
    assert abs(stage_sum + a["untracked_s"] - a["end_to_end_s"]) < 1e-6
    assert a["dominant_stage"] is not None
    assert "serve.execute" in a["stages"]
    assert "serve.batch_wait" in a["stages"]


def test_slo_percentiles_and_cli_after_n_requests(serve_ray, monkeypatch, capsys):
    """10 requests -> requests_total 10 and ordered latency
    percentiles in summarize_serve; the `serve status` CLI renders the
    same data."""
    from ray_tpu.util import state as state_api

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind())
    for i in range(10):
        assert handle.remote(i).result() == i

    deadline = time.monotonic() + 15
    dep = None
    while time.monotonic() < deadline:
        deps = state_api.summarize_serve()["deployments"]
        dep = deps.get("Echo")
        if dep and dep["routes"].get("", {}).get("requests", 0) >= 10:
            break
        time.sleep(0.1)
    assert dep is not None, "Echo never appeared in summarize_serve"
    r = dep["routes"][""]
    assert r["requests"] >= 10
    assert r["errors"] == 0 and r["timeouts"] == 0
    lat = r["latency_s"]
    assert lat is not None and lat["count"] >= 10
    assert lat["p50"] <= lat["p95"] <= lat["p99"]
    assert lat["mean"] > 0
    assert dep["replicas"] >= 1

    # CLI: same registry through `ray_tpu serve status`
    import json
    from types import SimpleNamespace

    from ray_tpu import scripts

    monkeypatch.setattr(scripts, "_connect", lambda args: None)
    scripts.cmd_serve(SimpleNamespace(format="json", address=None))
    out = json.loads(capsys.readouterr().out)
    assert out["deployments"]["Echo"]["routes"][""]["requests"] >= 10
    scripts.cmd_serve(SimpleNamespace(format="table", address=None))
    table = capsys.readouterr().out
    assert "Echo" in table and "P99_MS" in table


def test_batch_efficiency_partial_batch(serve_ray):
    """A single request against max_batch_size=8 fires a 1-wide batch:
    efficiency (mean actual/max) reports exactly 1/8."""
    from ray_tpu.util import state as state_api

    @serve.deployment
    class B:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01)
        async def handler(self, items):
            return [len(items)] * len(items)

        async def __call__(self, x):
            return await self.handler(x)

    handle = serve.run(B.bind())
    assert handle.remote(0).result() == 1  # batch of exactly one

    deadline = time.monotonic() + 15
    eff = None
    while time.monotonic() < deadline:
        dep = state_api.summarize_serve()["deployments"].get("B")
        if dep and dep["batch_efficiency"] is not None:
            eff = dep["batch_efficiency"]
            break
        time.sleep(0.1)
    assert eff is not None
    assert abs(eff - 1.0 / 8.0) < 1e-9


def test_sampling_zero_emits_no_spans(serve_ray):
    """Default sampling (0): a serve request must record no trace at
    all — span emission is entirely head-gated."""
    import os

    assert os.environ.get("RAY_TPU_TRACING") is None
    assert os.environ.get("RAY_TPU_TRACE_SAMPLE") is None

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind())
    assert handle.remote("a").result() == "a"
    time.sleep(0.5)  # give any (wrongly) emitted span time to land
    assert _client().list_state("traces") == []


def test_redeploy_drains_inflight_request(serve_ray):
    """Version-bump teardown waits for the in-flight request: the
    caller gets its answer from the OLD replica (no retry, no
    ActorDiedError), and the teardown books it drained, not dropped."""
    from ray_tpu.util import state as state_api

    @serve.deployment
    class Slow:
        def __call__(self, x):
            time.sleep(1.0)
            return x * 2

    handle = serve.run(Slow.bind())
    res = handle.remote(21)
    time.sleep(0.2)  # let it land on the v0 replica
    serve.run(Slow.options(max_ongoing_requests=8).bind())  # version bump
    assert res.result(timeout_s=30) == 42

    deadline = time.monotonic() + 15
    dep = None
    while time.monotonic() < deadline:
        dep = state_api.summarize_serve()["deployments"].get("Slow")
        if dep and dep["drained"] >= 1:
            break
        time.sleep(0.1)
    assert dep is not None
    assert dep["drained"] >= 1
    assert dep["dropped"] == 0
