"""graftlint: per-checker fixtures + the package-wide zero-findings gate.

Every checker has at least one flagged fixture (the bug shape, mirroring
real defects this repo has shipped) and one clean fixture (the fixed
shape). The gate test runs the analyzer over the whole ``ray_tpu``
package (not ``tests/``, which trips GL004 by design in its fixtures)
and fails on any non-baselined finding — so the invariants hold on
every tier-1 run.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.tools.graftlint import (
    DEFAULT_BASELINE_PATH,
    check_file,
    check_paths,
    load_baseline,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "ray_tpu")


def codes_of(source, path="fixture.py"):
    return sorted({f.code for f in check_file(path, source=textwrap.dedent(source))})


# Shared scaffolding: every rule family repeats the same three moves —
# run the CLI as a subprocess, materialize a throwaway fixture tree, or
# re-apply a historical defect to the REAL source and lint the modified
# copy against the rest of the live tree. Keep each shape in ONE place.


def run_cli(*args, cwd=REPO_ROOT):
    """Run ``python -m ray_tpu.tools.graftlint`` exactly as CI would."""
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.tools.graftlint", *map(str, args)],
        capture_output=True,
        text=True,
        env=dict(os.environ, PYTHONPATH=REPO_ROOT),
        cwd=str(cwd),
    )


def live_revert(rel_path, old, new, codes):
    """Fresh findings after replacing ``old`` with ``new`` in the real
    ``ray_tpu/<rel_path>`` (analyzed via overrides — disk untouched,
    every other file live). Asserts the anchor text still exists, so a
    refactor that silently invalidates the revert fails loudly instead
    of testing nothing."""
    path = os.path.join(PKG_DIR, *rel_path.split("/"))
    with open(path) as f:
        real = f.read()
    reverted = real.replace(old, new)
    assert reverted != real, f"{rel_path} no longer matches the revert"
    fresh, _ = check_paths(
        [PKG_DIR], overrides={path: reverted}, codes=set(codes)
    )
    return fresh


# --------------------------------------------------------------------- GL001


def test_gl001_flags_split_check_then_act():
    # mirrors the object_store.free() race: room checked under one
    # acquisition, pool mutated under another
    src = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._pool = []
            self._pool_bytes = 0

        def free(self, cap):
            with self._lock:
                room = self._pool_bytes + cap <= 100 and len(self._pool) < 8
            if room:
                with self._lock:
                    self._pool.append(cap)
                    self._pool_bytes += cap
    """
    assert "GL001" in codes_of(src)


def test_gl001_clean_when_check_and_act_share_one_acquisition():
    src = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._pool = []
            self._pool_bytes = 0

        def free(self, cap):
            with self._lock:
                if self._pool_bytes + cap <= 100 and len(self._pool) < 8:
                    self._pool.append(cap)
                    self._pool_bytes += cap
    """
    assert codes_of(src) == []


def test_gl001_clean_when_act_block_revalidates():
    # double-checked locking that re-tests under the acting acquisition
    src = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._pool = []

        def free(self, cap):
            with self._lock:
                room = len(self._pool) < 8
            if room:
                with self._lock:
                    if len(self._pool) < 8:
                        self._pool.append(cap)
    """
    assert codes_of(src) == []


def test_gl001_flags_unguarded_write():
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def incr(self):
            with self._lock:
                self._n += 1

        def sneak(self):
            self._n += 1
    """
    findings = check_file("fixture.py", source=textwrap.dedent(src))
    assert any(f.code == "GL001" and "sneak" in f.symbol for f in findings)


def test_gl001_allows_init_and_locked_writes():
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def incr(self):
            with self._lock:
                self._n += 1
    """
    assert codes_of(src) == []


def test_gl001_inline_suppression():
    src = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def incr(self):
            with self._lock:
                self._n += 1

        def sneak(self):
            self._n += 1  # graftlint: disable=GL001 — single-writer path
    """
    assert codes_of(src) == []


# --------------------------------------------------------------------- GL002


HUB_SHAPE = """
import threading

class Hub:
    def start(self):
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while self._running:
            for r in self.wait():
                try:
                    while True:
                        msg = r.recv()
                        try:
                            self.handle(r, msg)
                        except Exception:
                            self.log()
                        if not r.poll(0):
                            break
                except (EOFError, OSError):
                    self._handle_disconnect(r)
"""


def test_gl002_flags_narrow_except_doing_cleanup():
    # mirrors the hub reactor bug: _handle_disconnect raising
    # AttributeError escaped (EOFError, OSError) and killed the thread
    assert "GL002" in codes_of(HUB_SHAPE)


def test_gl002_clean_with_broad_arm():
    src = HUB_SHAPE + """
"""
    src = src.replace(
        "                except (EOFError, OSError):\n"
        "                    self._handle_disconnect(r)",
        "                except (EOFError, OSError):\n"
        "                    self._handle_disconnect(r)\n"
        "                except Exception:\n"
        "                    self.log()\n"
        "                    self._handle_disconnect(r)",
    )
    assert codes_of(src) == []


def test_gl002_ignores_pure_control_flow_handlers():
    # `except queue.Empty: break` is an idiomatic signal, not a bug
    src = """
    import queue
    import threading

    class Worker:
        def start(self):
            threading.Thread(target=self._loop, daemon=True).start()

        def _loop(self):
            while self._running:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                self.process(item)
    """
    assert codes_of(src) == []


def test_gl002_ignores_non_thread_functions():
    # same shape outside a Thread target: not a daemon-loop concern
    src = """
    def pump(conn):
        while True:
            try:
                conn.send(conn.recv())
            except (EOFError, OSError):
                conn.close()
    """
    assert codes_of(src) == []


def test_gl002_flags_loop_wrapped_by_narrow_try():
    src = """
    import threading

    class Client:
        def start(self):
            threading.Thread(target=self._read_loop, daemon=True).start()

        def _read_loop(self):
            try:
                while True:
                    self.dispatch(self.conn.recv())
            except (EOFError, OSError):
                self.fail_pending()
    """
    assert "GL002" in codes_of(src)


# --------------------------------------------------------------------- GL003


def test_gl003_flags_blocking_calls_in_async():
    src = """
    import subprocess
    import time

    async def handler(request):
        time.sleep(0.1)
        subprocess.run(["ls"])
        return request
    """
    findings = check_file("fixture.py", source=textwrap.dedent(src))
    assert sum(f.code == "GL003" for f in findings) == 2


def test_gl003_resolves_import_aliases():
    src = """
    from time import sleep

    async def handler(request):
        sleep(0.1)
    """
    assert "GL003" in codes_of(src)


def test_gl003_clean_async_and_nested_sync():
    src = """
    import asyncio
    import time

    async def handler(request):
        await asyncio.sleep(0.1)

        def sync_helper():
            time.sleep(0.1)  # runs wherever it's *called*, not here

        return sync_helper
    """
    assert codes_of(src) == []


def test_gl003_ignores_sync_functions():
    src = """
    import time

    def poll():
        time.sleep(0.1)
    """
    assert codes_of(src) == []


# --------------------------------------------------------------------- GL004


def test_gl004_flags_discarded_object_ref():
    src = """
    def fire(actor):
        actor.ping.remote()
    """
    assert "GL004" in codes_of(src)


def test_gl004_clean_when_ref_is_kept():
    src = """
    import ray_tpu

    def fire(actor):
        ref = actor.ping.remote()
        return ray_tpu.get(ref)
    """
    assert codes_of(src) == []


def test_gl004_flags_get_of_fresh_ref_in_loop():
    src = """
    import ray_tpu

    def poll_all(actors):
        out = []
        for a in actors:
            out.append(ray_tpu.get(a.step.remote()))
        return out
    """
    assert "GL004" in codes_of(src)


def test_gl004_flags_get_of_fresh_ref_in_comprehension():
    # the comprehension spelling of the serialized round-trip — the
    # natural "rewrite" of a flagged for-loop — must stay flagged
    src = """
    import ray_tpu

    def poll_all(actors):
        return [ray_tpu.get(a.step.remote()) for a in actors]
    """
    assert "GL004" in codes_of(src)


def test_gl004_clean_batched_get():
    # getting a list of refs submitted together is the good pattern,
    # even inside an outer loop
    src = """
    import ray_tpu

    def train(runners):
        for _ in range(10):
            rollouts = ray_tpu.get([r.sample.remote() for r in runners])
            consume(rollouts)
    """
    assert codes_of(src) == []


def test_gl004_flags_lock_passed_to_remote():
    src = """
    import threading

    def submit(actor):
        lock = threading.Lock()
        return actor.run.remote(lock)
    """
    assert "GL004" in codes_of(src)


def test_gl004_flags_self_lock_arg():
    src = """
    class Driver:
        def submit(self, actor):
            return actor.run.remote(self._lock)
    """
    assert "GL004" in codes_of(src)


def test_gl004_flags_lock_passed_as_keyword():
    src = """
    class Driver:
        def submit(self, actor):
            return actor.run.remote(arg=self._lock)
    """
    assert "GL004" in codes_of(src)


def test_gl004_clean_plain_args():
    src = """
    def submit(actor, payload):
        return actor.run.remote(payload, 3, key="v")
    """
    assert codes_of(src) == []


# --------------------------------------------------------------------- GL005


def test_gl005_flags_unbounded_instance_list():
    # mirrors MultiAgentEnvRunner.completed_returns: appended per
    # finished episode, only the [-100:] window ever read
    src = """
    class Runner:
        def __init__(self):
            self.completed_returns = []

        def sample(self):
            for ep in self.episodes:
                if ep.is_done:
                    self.completed_returns.append(ep.total_return())
            return self.completed_returns[-100:]
    """
    assert "GL005" in codes_of(src)


def test_gl005_flags_annotated_init():
    src = """
    from typing import List

    class Runner:
        def __init__(self):
            self.completed_returns: List[float] = []

        def sample(self):
            for ep in self.episodes:
                self.completed_returns.append(ep.ret)
    """
    assert "GL005" in codes_of(src)


def test_gl005_clean_with_deque_maxlen():
    src = """
    from collections import deque

    class Runner:
        def __init__(self):
            self.completed_returns = deque(maxlen=100)

        def sample(self):
            for ep in self.episodes:
                if ep.is_done:
                    self.completed_returns.append(ep.total_return())
            return list(self.completed_returns)
    """
    assert codes_of(src) == []


def test_gl005_clean_when_trimmed_or_reassigned():
    src = """
    class Batcher:
        def __init__(self):
            self.buf = []

        def add_all(self, items):
            for it in items:
                self.buf.append(it)

        def drain(self):
            out, self.buf = self.buf, []
            return out
    """
    assert codes_of(src) == []


def test_gl005_module_level_and_memo_exemption():
    flagged = """
    LOG = []

    def record(events):
        for e in events:
            LOG.append(e)
    """
    assert "GL005" in codes_of(flagged)
    memo = """
    TABLE = []

    def table():
        if not TABLE:
            for i in range(256):
                TABLE.append(i * 7)
        return TABLE
    """
    assert codes_of(memo) == []


# --------------------------------------------------------------------- GL006


def test_gl006_flags_ones_seeded_accumulator():
    # mirrors NormalizeObservations._m2: a += accumulator seeded ones
    src = """
    import numpy as np

    class Norm:
        def update(self, batch):
            if self._m2 is None:
                self._mean = np.zeros(4)
                self._m2 = np.ones(4)
            self._m2 += batch.var(axis=0)
    """
    assert "GL006" in codes_of(src)


def test_gl006_clean_zeros_seed_and_non_accumulated_ones():
    src = """
    import numpy as np

    class Norm:
        def update(self, batch):
            if self._m2 is None:
                self._m2 = np.zeros(4)
                self._scale = np.ones(4)  # multiplicative: ones is right
            self._m2 += batch.var(axis=0)
            self._scale = self._scale * 0.99
    """
    assert codes_of(src) == []


# --------------------------------------------------------------------- GL007


def test_gl007_flags_fstring_getattr_in_while_loop():
    src = """
    class Hub:
        def _run(self):
            while self._running:
                msg_type, payload = self.recv()
                handler = getattr(self, f"_on_{msg_type}", None)
                if handler is not None:
                    handler(payload)
    """
    assert "GL007" in codes_of(src)


def test_gl007_flags_fstring_getattr_in_for_loop():
    src = """
    class Hub:
        def _handle_batch(self, conn, payload):
            for mt, pl in payload:
                h = getattr(self, f"_on_{mt}", None)
                if h is not None:
                    h(conn, pl)
    """
    assert "GL007" in codes_of(src)


def test_gl007_flags_concat_percent_and_format_spellings():
    # the natural revert spellings of the f-string shape must not
    # slip past the gate
    for name_expr in (
        '"_on_" + msg_type',
        '"_on_%s" % msg_type',
        '"_on_{}".format(msg_type)',
    ):
        src = f"""
    class Hub:
        def _run(self):
            while self._running:
                msg_type = self.recv()
                handler = getattr(self, {name_expr}, None)
    """
        assert "GL007" in codes_of(src), name_expr


def test_gl007_clean_precomputed_name_variable():
    # passing an already-computed name through getattr in a loop is the
    # table/probe pattern, not per-message string building
    src = """
    def probe(objs, name):
        out = []
        for o in objs:
            out.append(getattr(o, name, None))
        return out
    """
    assert codes_of(src) == []


def test_gl007_clean_table_dispatch():
    # the fixed shape: table built once, dict lookup in the loop
    src = """
    class Hub:
        def __init__(self):
            self._handlers = {
                name[4:]: getattr(self, name)
                for name in dir(type(self))
                if name.startswith("_on_")
            }

        def _run(self):
            while self._running:
                msg_type, payload = self.recv()
                handler = self._handlers.get(msg_type)
                if handler is not None:
                    handler(payload)
    """
    assert codes_of(src) == []


def test_gl007_ignores_one_off_reflection_outside_loops():
    # CLI subcommand resolution: reflection, but not per-message
    src = """
    def cmd_list(args, state_api):
        fn = getattr(state_api, f"list_{args.kind}")
        return fn()
    """
    assert codes_of(src) == []


def test_gl007_symbol_is_enclosing_function():
    src = """
    class Hub:
        def _run(self):
            while True:
                h = getattr(self, f"_on_{self.recv()}", None)
    """
    findings = [
        f for f in check_file("x.py", source=textwrap.dedent(src))
        if f.code == "GL007"
    ]
    assert len(findings) == 1
    assert findings[0].symbol == "Hub._run"


# --------------------------------------------------------------------- GL008

_PRIV = "ray_tpu/_private/fixture.py"


def test_gl008_flags_wall_clock_delta():
    # the classic stamp-and-subtract duration, spelled with time.time()
    src = """
    import time

    def handle(self, msg):
        t0 = time.time()
        self.dispatch(msg)
        self.latency.observe(time.time() - t0)
    """
    assert "GL008" in codes_of(src, path=_PRIV)


def test_gl008_flags_from_import_spelling():
    src = """
    from time import time

    def run(self):
        start = time()
        self.step()
        return time() - start
    """
    assert "GL008" in codes_of(src, path=_PRIV)


def test_gl008_clean_monotonic_duration():
    src = """
    import time

    def handle(self, msg):
        t0 = time.monotonic()
        self.dispatch(msg)
        self.latency.observe(time.monotonic() - t0)
    """
    assert codes_of(src, path=_PRIV) == []


def test_gl008_clean_mtime_comparison():
    # file mtimes ARE wall clock: comparing them against time.time()
    # is the only correct spelling (runtime-env stale-lock breaker)
    src = """
    import os
    import time

    def stale(lock):
        return time.time() - os.path.getmtime(lock) > 300
    """
    assert codes_of(src, path=_PRIV) == []


def test_gl008_clean_mtime_through_local_name():
    # provenance tracks through locals symmetrically: an mtime stored
    # in a variable still exempts the subtraction
    src = """
    import os
    import time

    def stale(lock):
        stamped = os.path.getmtime(lock)
        now = time.time()
        return now - stamped > 300
    """
    assert codes_of(src, path=_PRIV) == []


def test_gl008_clean_wall_timestamp_without_delta():
    # absolute wall stamps (timeline positions, usage reports) are fine
    src = """
    import time

    def stamp(ev):
        ev["submitted_at"] = time.time()
        ev["ms"] = int(time.time() * 1000)
    """
    assert codes_of(src, path=_PRIV) == []


def test_gl008_scope_covers_private_and_tracing():
    # runtime core AND util/tracing.py (span durations feed the
    # critical-path analyzer — a wall-delta duration there regresses
    # the very thing the tracer exists to measure); other user-facing
    # code legitimately carries wall timestamps
    src = """
    import time

    def span():
        t0 = time.time()
        return time.time() - t0
    """
    assert "GL008" in codes_of(src, path="ray_tpu/util/tracing.py")
    assert "GL008" in codes_of(src, path=_PRIV)
    assert codes_of(src, path="ray_tpu/util/metrics.py") == []


# ---------------------------------------------------------- infrastructure


def test_baseline_round_trip(tmp_path):
    src = textwrap.dedent(
        """
        def fire(actor):
            actor.ping.remote()
        """
    )
    f = tmp_path / "mod.py"
    f.write_text(src)
    new, old = check_paths([str(f)])
    assert [x.code for x in new] == ["GL004"] and old == []
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, new)
    baseline = load_baseline(bl_path)
    new2, old2 = check_paths([str(f)], baseline=baseline)
    assert new2 == [] and [x.code for x in old2] == ["GL004"]
    # fingerprints are line-insensitive: shifting the file doesn't
    # invalidate the baseline entry
    f.write_text("# a new leading comment\n" + src)
    new3, old3 = check_paths([str(f)], baseline=baseline)
    assert new3 == [] and len(old3) == 1


def test_syntax_error_reports_gl000(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text("def broken(:\n")
    findings = check_file(str(f))
    assert [x.code for x in findings] == ["GL000"]


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def fire(actor):\n    actor.ping.remote()\n")
    good = tmp_path / "good.py"
    good.write_text("def add(a, b):\n    return a + b\n")

    r = run_cli(good)
    assert r.returncode == 0, r.stdout + r.stderr

    r = run_cli(bad)
    assert r.returncode == 1
    assert "GL004" in r.stdout

    # --write-baseline accepts the findings; a rerun against it is clean
    bl = tmp_path / "bl.json"
    r = run_cli(bad, "--write-baseline", bl)
    assert r.returncode == 0
    assert json.loads(bl.read_text())["entries"]
    r = run_cli(bad, "--baseline", bl)
    assert r.returncode == 0

    r = run_cli(tmp_path / "missing.py")
    assert r.returncode == 2

    # a typo'd --select must not silently run zero checkers and pass
    r = run_cli(bad, "--select", "GL04")
    assert r.returncode == 2
    assert "unknown rule code" in r.stderr

    # an explicitly-named file is linted even without a .py extension
    script = tmp_path / "worker_script"
    script.write_text(bad.read_text())
    r = run_cli(script)
    assert r.returncode == 1
    assert "GL004" in r.stdout


def test_same_named_methods_get_distinct_fingerprints():
    # two classes with a same-named reactor method must not share a
    # baseline fingerprint, or baselining one hides the other
    src = textwrap.dedent("""
    import threading

    class A:
        def start(self):
            threading.Thread(target=self._run).start()

        def _run(self):
            while True:
                try:
                    self.step()
                except OSError:
                    self.cleanup()

    class B:
        def start(self):
            threading.Thread(target=self._run).start()

        def _run(self):
            while True:
                try:
                    self.step()
                except OSError:
                    self.cleanup()
    """)
    findings = [
        f for f in check_file("x.py", source=src) if f.code == "GL002"
    ]
    assert len(findings) == 2
    assert len({f.fingerprint() for f in findings}) == 2


def test_gl003_nested_coroutine_reported_once():
    src = textwrap.dedent("""
    import time

    async def outer():
        async def inner():
            time.sleep(1)
        await inner()
    """)
    findings = [
        f for f in check_file("x.py", source=src) if f.code == "GL003"
    ]
    assert len(findings) == 1
    assert "inner" in findings[0].symbol


# ------------------------------------------------- the shipped bugs


def test_reverting_hub_disconnect_fix_is_flagged():
    """The hub bug: `except (EOFError, OSError)` around the recv loop
    called _handle_disconnect, whose _client_puts cleanup raised
    AttributeError on ('failed', msg) tombstones — killing the hub."""
    assert "GL002" in codes_of(HUB_SHAPE)


def test_reverting_object_store_free_fix_is_flagged():
    src = """
    import os
    import threading
    import uuid

    class ShmObjectStore:
        def __init__(self):
            self._lock = threading.Lock()
            self._segments = {}
            self._pool = []
            self._pool_bytes = 0

        def free(self, name):
            with self._lock:
                seg = self._segments.pop(name, None)
            if seg is not None and seg.writable:
                cap = len(seg.mm)
                with self._lock:
                    room = (
                        self._pool_bytes + cap <= 2**31
                        and len(self._pool) < 8
                    )
                if room:
                    pooled = f".pool.{uuid.uuid4().hex}"
                    os.rename(seg.path, pooled)
                    seg.path = pooled
                    with self._lock:
                        self._pool.append((cap, seg))
                        self._pool_bytes += cap
    """
    assert "GL005" not in codes_of(src)
    assert "GL001" in codes_of(src)


def test_reverting_connectors_m2_fix_is_flagged():
    src = """
    import numpy as np

    class NormalizeObservations:
        def __call__(self, batch):
            flat = np.asarray(batch["obs"])
            if self._mean is None:
                self._mean = np.zeros(flat.shape[1], np.float64)
                self._m2 = np.ones(flat.shape[1], np.float64)
            self._m2 += ((flat - flat.mean(0)) ** 2).sum(0)
    """
    assert "GL006" in codes_of(src)


def test_reverting_multi_agent_deque_fix_is_flagged():
    src = """
    from typing import List, Optional

    class MultiAgentEnvRunner:
        def __init__(self, num_envs=1):
            self.episodes: List[Optional[object]] = [None] * num_envs
            self.completed_returns: List[float] = []

        def sample(self):
            for i, ep in enumerate(self.episodes):
                if ep.is_done:
                    self.completed_returns.append(ep.total_return())
            return self.completed_returns[-100:]
    """
    assert "GL005" in codes_of(src)


def test_reverting_hub_dispatch_table_is_flagged():
    """The PR-2 hot-path fix: hub._handle resolved handlers with
    getattr(self, f"_on_{mt}") per message inside the batch drain loop;
    reverting to that shape must trip GL007."""
    src = """
    class Hub:
        def _handle(self, conn, msg_type, payload):
            if msg_type == "batch":
                for mt, pl in payload:
                    h = getattr(self, f"_on_{mt}", None)
                    if h is not None:
                        h(conn, pl)
                return
            handler = getattr(self, f"_on_{msg_type}", None)
            if handler is None:
                return
            handler(conn, payload)
    """
    assert "GL007" in codes_of(src)


def test_reverting_hub_timeline_wall_duration_is_flagged():
    """The PR-4 lifecycle fix: timeline slice durations used to come
    from wall-clock stamp deltas (`end = finished_at or time.time()`,
    `end - started_at`); durations now subtract the monotonic t_*
    twins. Reverting to the wall-delta shape must trip GL008."""
    src = """
    import time

    class Hub:
        def _on_list_state(self, conn, p):
            items = []
            for ev in self.task_events:
                end = ev.get("finished_at") or time.time()
                items.append({
                    "ts": ev["started_at"] * 1e6,
                    "dur": max(0.0, (end - ev["started_at"]) * 1e6),
                })
            return items
    """
    assert "GL008" in codes_of(src, path="ray_tpu/_private/hub.py")


# --------------------------------------------------------------------- GL009


def test_gl009_flags_handler_registry_without_prune():
    # the hub-registry leak shape: a message handler inserts into a
    # dict born empty in __init__, and no method ever removes entries
    src = """
    class Hub:
        def __init__(self):
            self.jobs = {}

        def _on_register_job(self, conn, p):
            self.jobs[p["job_id"]] = (p["tenant"], p["priority"])
    """
    assert "GL009" in codes_of(src)


def test_gl009_flags_setdefault_and_append_growth():
    src = """
    class Hub:
        def __init__(self):
            self.waiters = {}
            self.log = []

        def _on_wait(self, conn, p):
            self.waiters.setdefault(p["oid"], []).append(conn)

        def _on_note(self, conn, p):
            self.log.append(p)
    """
    assert "GL009" in codes_of(src)


def test_gl009_clean_when_disconnect_prunes():
    src = """
    class Hub:
        def __init__(self):
            self.jobs = {}

        def _on_register_job(self, conn, p):
            self.jobs[p["job_id"]] = (p["tenant"], p["priority"])

        def _handle_disconnect(self, conn):
            for job_id in [j for j, e in self.jobs.items() if e[0] == conn]:
                self.jobs.pop(job_id, None)
    """
    assert "GL009" not in codes_of(src)


def test_gl009_clean_when_del_or_reassigned():
    src = """
    class Hub:
        def __init__(self):
            self.table = {}

        def _on_put(self, conn, p):
            self.table[p["k"]] = p["v"]

        def _gc(self):
            for k in self._expired():
                del self.table[k]
    """
    assert "GL009" not in codes_of(src)


def test_gl009_ignores_non_handler_growth_and_seeded_tables():
    # growth outside _on_*/register_* methods has its own lifecycle;
    # tables seeded non-empty are static maps, not request registries
    src = """
    class Client:
        def __init__(self):
            self.cache = {}
            self.nodes = {"node0": object()}

        def get(self, k, v):
            self.cache[k] = v

        def _on_register_node(self, conn, p):
            self.nodes[p["node_id"]] = p
    """
    assert "GL009" not in codes_of(src)


def test_reverting_fairsched_job_registry_prune_is_flagged():
    """The PR-5 JobEntry registry: FairScheduler.register_job inserts
    into self.jobs and drop_conn (wired into the hub's disconnect
    path) prunes it. Removing the prune must trip GL009."""
    src = """
    class FairScheduler:
        def __init__(self, clock=None):
            self.jobs = {}
            self.tenants = {}

        def register_job(self, job_id, tenant, priority, quota, conn_id):
            entry = self.jobs[job_id] = (tenant, priority, quota, conn_id)
            return entry

        def drop_conn(self, conn_id):
            return []  # prune removed: the registry now grows forever
    """
    assert "GL009" in codes_of(src)
    # ...and the shipped shape (drop_conn deletes by conn id) is clean
    fixed = """
    class FairScheduler:
        def __init__(self, clock=None):
            self.jobs = {}
            self.tenants = {}

        def register_job(self, job_id, tenant, priority, quota, conn_id):
            entry = self.jobs[job_id] = (tenant, priority, quota, conn_id)
            return entry

        def drop_conn(self, conn_id):
            gone = [j for j, e in self.jobs.items() if e[3] == conn_id]
            for job_id in gone:
                del self.jobs[job_id]
            return gone
    """
    assert "GL009" not in codes_of(fixed)


# --------------------------------------------------------------------- GL010


def test_gl010_flags_shard_touching_hub_state():
    # the bug class the multi-reactor split exists to remove: a reactor
    # shard mutating hub tables directly from its own thread
    src = """
    class ReactorShard:
        def __init__(self, hub):
            self.hub = hub

        def _drain_conn(self, conn):
            blob = conn.recv_bytes()
            self.hub.objects[blob] = True
            self.hub.tasks.pop(blob, None)
    """
    codes = codes_of(src)
    assert "GL010" in codes


def test_gl010_flags_peer_shard_state_via_alias():
    # aliasing a peer shard into a local does not launder the access
    src = """
    class ReactorShard:
        def _accept(self, conn):
            target = self.peers[0]
            target.selector.register(conn)
    """
    assert "GL010" in codes_of(src)


def test_gl010_clean_for_message_queue_api():
    # the shipped shape: rings + the adopt/post control surface only
    src = """
    class ReactorShard:
        def _accept(self, conn):
            target = self.peers[0]
            if target is self:
                self._register(conn)
            else:
                target.adopt(conn)

        def _drain_conn(self, conn):
            blob = conn.recv_bytes()
            self._state_ring.push((conn, None, "put", blob))

        def _flush(self):
            for conn, msgs in self.outbound.drain():
                conn.send_bytes(msgs)
    """
    assert "GL010" not in codes_of(src)


def test_gl010_ignores_non_reactor_classes():
    # the state plane (Hub) legitimately owns hub/service state; only
    # reactor-marked classes are in scope
    src = """
    class Hub:
        def _state_loop(self, hub):
            hub.objects.clear()
            self.services.update({})
    """
    assert "GL010" not in codes_of(src)


def test_reverting_shard_direct_disconnect_is_flagged():
    """The real violation GL010 was written against: the first draft of
    the shard refactor had ReactorShard._drop_conn calling
    hub._handle_disconnect(conn) directly from the shard thread —
    racing the state plane over every registry the cleanup touches.
    The shipped shape pushes a CONN_LOST message instead. Re-applying
    the direct call to the REAL hub_shards.py source must trip GL010."""
    fresh = live_revert(
        "_private/hub_shards.py",
        "self._state_ring.push((conn, None, CONN_LOST, None))",
        "self.hub._handle_disconnect(conn)",
        codes={"GL010"},
    )
    assert "GL010" in {f.code for f in fresh}, [f.render() for f in fresh]


# --------------------------------------------------------------------- GL011


_GL011_OLD_LOOP = """
    from concurrent.futures import wait as _fut_wait

    class C:
        _RETRY_PERIOD_S = 2.0

        def request(self, msg_type, payload, fut, timeout=None):
            import time as _time
            deadline = (
                None if timeout is None else _time.monotonic() + timeout
            )
            while True:
                remaining = self._RETRY_PERIOD_S
                if deadline is not None:
                    remaining = min(remaining, deadline - _time.monotonic())
                    if remaining <= 0:
                        raise TimeoutError()
                _fut_wait([fut], timeout=remaining)
                if fut.done():
                    return fut.result()
                self.send(msg_type, payload)
"""


def test_gl011_flags_fixed_interval_retransmit():
    # the shipped bug shape: the pre-fix GET retransmit loop — fixed
    # ~2s cadence (the deadline min() is a clamp, not a backoff term)
    assert "GL011" in codes_of(_GL011_OLD_LOOP, path=_PRIV)


def test_gl011_flags_literal_cadence():
    src = """
    def pump(self):
        while not self.done:
            self.evt.wait(2.0)
            self.conn.send_bytes(self.frame)
    """
    assert "GL011" in codes_of(src, path=_PRIV)


def test_gl011_clean_with_multiplicative_backoff():
    src = """
    def pump(self):
        delay = 0.2
        while not self.done:
            self.evt.wait(delay)
            self.conn.send_bytes(self.frame)
            delay = min(30.0, delay * 2.0)
    """
    assert "GL011" not in codes_of(src, path=_PRIV)


def test_gl011_clean_with_backoff_helper_and_derived_delay():
    # the shipped fix shape: the wait duration derives from a variable
    # grown through a helper call (tuple unpack) — dataflow closure
    # must see through the derivation
    fixed = _GL011_OLD_LOOP.replace(
        "remaining = self._RETRY_PERIOD_S",
        "remaining, delay = self._retry_delay(delay)",
    )
    assert "GL011" not in codes_of(fixed, path=_PRIV)


def test_gl011_clean_with_conditional_backoff_helper():
    # the _wait_push shape: each wait is drawn from the helper, but the
    # growth step is applied CONDITIONALLY through a second unpacked
    # name (`cur = nxt` only when the wait timed out) — still backoff
    src = """
    def pump(self):
        cur = 0.2
        while not self.done:
            remaining, nxt = self._retry_delay(cur, cap=8.0)
            if not self.evt.wait(remaining):
                cur = nxt
                self.conn.send_bytes(self.frame)
            else:
                cur = 0.2
    """
    assert "GL011" not in codes_of(src, path=_PRIV)


def test_gl011_clean_heartbeat_and_flush_loops():
    # periodic SENDERS are not retransmit loops: a heartbeat paced on
    # conn.poll, and a flush loop with no resend call
    src = """
    def run(self):
        while True:
            if self.conn.poll(1.0):
                self.handle()
            self.heartbeat()

    def flush_loop(self):
        while not self.closed:
            self.evt.wait(timeout=0.25)
            self.flush()
    """
    assert "GL011" not in codes_of(src, path=_PRIV)


def test_gl011_scope_covers_private_and_serve():
    # PR 15 widened the scope: the serve plane grew its own retransmit
    # loops (handle transparent retry, ejection re-probe), so
    # ray_tpu/serve/ is gated alongside every _private/ package.
    # Library/util code stays out of scope.
    assert "GL011" in codes_of(_GL011_OLD_LOOP, path="ray_tpu/serve/x.py")
    assert "GL011" in codes_of(
        _GL011_OLD_LOOP, path="ray_tpu/serve/_private/x.py"
    )
    assert "GL011" not in codes_of(_GL011_OLD_LOOP, path="ray_tpu/util/x.py")


def test_gl011_flags_fixed_interval_remote_reprobe():
    # the serve resend spelling: actor_method.remote(...) re-dispatch on
    # a fixed cadence is the same storm shape as a wire-level resend
    src = """
    def probe(self):
        while self.targets:
            self.evt.wait(0.25)
            for replica in self.targets:
                replica.check_health.remote()
    """
    assert "GL011" in codes_of(src, path="ray_tpu/serve/handle.py")


def test_reverting_prober_fixed_cadence_is_flagged():
    """The ejection re-probe loop in the REAL handle.py backs off with
    delay = min(cap, delay * 2.0); flattening that growth back to a
    fixed cadence must trip GL011 now that serve/ is in scope."""
    fresh = live_revert(
        "serve/handle.py",
        "delay = min(cap, delay * 2.0)",
        "delay = base",
        codes={"GL011"},
    )
    assert "GL011" in {f.code for f in fresh}, [f.render() for f in fresh]


def test_reverting_client_fixed_retransmit_is_flagged():
    """The real bug GL011 was written against: CoreClient.request
    re-sent a parked request every fixed _RETRY_PERIOD_S forever. The
    shipped fix draws each wait from _retry_delay (capped exponential
    backoff + jitter); re-applying the fixed-period wait to the REAL
    client.py source must trip GL011."""
    fresh = live_revert(
        "_private/client.py",
        "remaining, delay = self._retry_delay(delay)",
        "remaining = self._RETRY_PERIOD_S",
        codes={"GL011"},
    )
    assert "GL011" in {f.code for f in fresh}, [f.render() for f in fresh]


# --------------------------------------------------------------------- GL018


_GL018_SUBMIT_LOOP = """
    import pickle

    def submit_all(self, fn_id, resources, options, tasks):
        for t in tasks:
            head = pickle.dumps(
                {"fn_id": fn_id, "resources": resources,
                 "options": options}
            )
            self.conn.send_bytes(head + t)
"""


def test_gl018_flags_invariant_header_reencoded_per_send():
    # the pre-splice submit shape: the (fn_id, resources, options)
    # header pickled once PER TASK inside the send loop
    assert "GL018" in codes_of(_GL018_SUBMIT_LOOP, path=_PRIV)


def test_gl018_flags_while_loop_retransmit_reencode():
    # same bug in its retransmit spelling: the frame re-encoded on
    # every resend instead of cached once (_resend_raw ships bytes)
    src = """
    def retransmit(self, msg_type, payload, fut):
        while not fut.done():
            self.evt.wait(0.2)
            self.conn.send_bytes(dumps_frame((msg_type, payload)))
    """
    assert "GL018" in codes_of(src, path=_PRIV)


def test_gl018_clean_when_encode_hoisted():
    # the fix shape: one encode above the loop
    src = """
    import pickle

    def submit_all(self, fn_id, resources, options, tasks):
        head = pickle.dumps(
            {"fn_id": fn_id, "resources": resources, "options": options}
        )
        for t in tasks:
            self.conn.send_bytes(head + t)
    """
    assert "GL018" not in codes_of(src, path=_PRIV)


def test_gl018_clean_when_payload_varies_per_iteration():
    # the encoded dict reads the loop variable: a genuinely per-call
    # payload, not a hoistable invariant
    src = """
    import pickle

    def submit_all(self, fn_id, tasks):
        for t in tasks:
            self.conn.send_bytes(
                pickle.dumps({"fn_id": fn_id, "task": t})
            )
    """
    assert "GL018" not in codes_of(src, path=_PRIV)


def test_gl018_clean_on_dynamic_expression():
    # a nested call can yield a fresh value per iteration even from
    # invariant inputs — the checker must not guess
    src = """
    def submit_all(self, options, tasks):
        for t in tasks:
            self.conn.send_bytes(dumps(self._header(options)))
    """
    assert "GL018" not in codes_of(src, path=_PRIV)


def test_gl018_clean_when_loop_rebinds_the_attribute():
    src = """
    def pump(self):
        while self.live:
            self.frame = self.advance()
            self.conn.send_bytes(dumps(self.frame))
    """
    assert "GL018" not in codes_of(src, path=_PRIV)


def test_gl018_clean_without_a_send_in_the_loop():
    # encode-only loops (codecs, tests building corpora) are not the
    # hot path this rule protects
    src = """
    import pickle

    def encode_all(self, header, tasks):
        out = []
        for _t in tasks:
            out.append(pickle.dumps(header))
        return out
    """
    assert "GL018" not in codes_of(src, path=_PRIV)


def test_gl018_scope_is_runtime_core():
    # remote_function.py owns the .remote() staging path and is gated
    # alongside _private/; library/util code stays out of scope
    assert "GL018" in codes_of(
        _GL018_SUBMIT_LOOP, path="ray_tpu/remote_function.py"
    )
    assert "GL018" not in codes_of(
        _GL018_SUBMIT_LOOP, path="ray_tpu/util/x.py"
    )


def test_reverting_per_fragment_reencode_is_flagged():
    """The bug GL018 was written against: before the spliced-template
    path, the submit pipeline re-encoded the invariant batch header
    once per task. Re-applying a per-fragment re-encode + send loop to
    the REAL _drain_autobatch_locked must trip GL018 against the live
    tree."""
    fresh = live_revert(
        "_private/client.py",
        "        if send:\n"
        "            self.conn.send_bytes(frame)",
        "        if send:\n"
        "            for _frag in frags:\n"
        "                head = dumps_frame((P.SUBMIT_TASKS, prefix))\n"
        "                self.conn.send_bytes(head)",
        codes={"GL018"},
    )
    assert "GL018" in {f.code for f in fresh}, [f.render() for f in fresh]


# ------------------------------------------------------------- repo gate


def test_repo_is_clean_under_graftlint():
    """The tier-1 gate: zero non-baselined findings over ray_tpu/.

    If this fails, either fix the flagged code, suppress the line with
    `# graftlint: disable=GLxxx — why`, or (for accepted debt) add the
    fingerprint to ray_tpu/tools/graftlint/baseline.json.
    """
    baseline = load_baseline(DEFAULT_BASELINE_PATH)
    new, _old = check_paths([PKG_DIR], baseline=baseline)
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in new
    )


def test_every_checker_is_exercised_by_the_gate_config():
    from ray_tpu.tools.graftlint import all_checkers, all_project_checkers

    codes = {code for code, _name, _fn in all_checkers()}
    assert codes == {
        "GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
        "GL008", "GL009", "GL010", "GL011", "GL018",
    }
    # the whole-program passes run through the same gate (check_paths
    # builds one ProjectSession over the package and runs them after
    # the per-file rules)
    pcodes = {code for code, _name, _fn in all_project_checkers()}
    assert pcodes == {
        "GL012", "GL013", "GL014", "GL015", "GL016", "GL017",
    }


# --------------------------------------------------------------------- GL012
#
# Protocol conformance needs a *session*: the contract lives in a
# protocol module, send sites and dispatch tables live elsewhere. The
# helper materializes a small multi-module project and runs only the
# selected pass over it.


def project_findings(tmp_path, files, codes):
    d = tmp_path / "proj"
    d.mkdir(exist_ok=True)
    for name, src in files.items():
        # names may carry directories ("ray_tpu/serve/app.py") for
        # path-scoped passes like GL017
        target = d / name
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(textwrap.dedent(src))
    new, _old = check_paths([str(d)], codes=set(codes))
    return new


GL012_PROTOCOL = """
PING = "ping"
PONG = "pong"
GONE = "gone"
"""

GL012_HUB = """
import protocol as P

class Hub:
    def __init__(self):
        self._handlers = {
            name[len("_on_"):]: getattr(self, name)
            for name in dir(type(self))
            if name.startswith("_on_")
        }

    def _on_ping(self, conn, p):
        return p["x"] + p.get("opt", 0)

    def _on_gone(self, conn, p):
        return p["why"]
"""


def test_gl012_flags_the_conformance_matrix(tmp_path):
    # one fixture, four defect classes: a send omitting a required key,
    # a sent-but-unhandled type, a handled-but-never-sent type, and a
    # raw string that bypasses the protocol module
    client = """
    import protocol as P

    class Client:
        def go(self, conn):
            self.send(P.PING, {"y": 1})
            self.send(P.PONG, {"z": 2})
            self.send("pingg", {})
    """
    new = project_findings(
        tmp_path,
        {"protocol.py": GL012_PROTOCOL, "hub.py": GL012_HUB,
         "client.py": client},
        {"GL012"},
    )
    symbols = {f.symbol for f in new}
    assert "<protocol>.pingg.unregistered" in symbols
    assert "<protocol>.pong.unhandled" in symbols
    assert "<protocol>.gone.never_sent" in symbols
    # the send site misses the unconditionally-read key 'x'...
    assert any(s.endswith(".ping.x.missing") for s in symbols), symbols
    # ...and ships a key no handler reads ('y'); the .get-read 'opt'
    # stays optional and unflagged
    assert "<protocol>.ping.y.never_read" in symbols
    assert not any(".opt." in s for s in symbols)


def test_gl012_clean_on_a_conforming_project(tmp_path):
    client = """
    import protocol as P

    class Client:
        def go(self, conn):
            self.send(P.PING, {"x": 1, "opt": 2})
            self.send(P.GONE, {"why": "done"})
            self.send(P.PONG, {"z": 2})

        def _poll(self, conn):
            mt, p = self.recv()
            if mt == P.PONG:
                return p["z"]
    """
    new = project_findings(
        tmp_path,
        {"protocol.py": GL012_PROTOCOL, "hub.py": GL012_HUB,
         "client.py": client},
        {"GL012"},
    )
    # PONG has no dispatch-table handler, but the client *compares*
    # against it inline (the request/response idiom) — consumed
    assert new == [], [f.render() for f in new]


def test_gl012_topology_parity_between_reactor_and_shards(tmp_path):
    # the single-reactor handler table and the sharded routing sets
    # must cover the identical message set
    proto = """
    A = "a"
    B = "b"
    D = "d"
    E = "e"
    """
    hub = """
    import protocol as P

    class Hub:
        def __init__(self):
            self._handlers = {
                name[len("_on_"):]: getattr(self, name)
                for name in dir(type(self))
                if name.startswith("_on_")
            }

        def _on_a(self, conn, p):
            return 1

        def _on_b(self, conn, p):
            return 2

        def _on_d(self, conn, p):
            return 3
    """
    shards = """
    SCHEDULER_MSGS = frozenset({"a", "b", "e"})
    """
    client = """
    import protocol as P

    class Client:
        def go(self):
            self.send(P.A, {})
            self.send(P.B, {})
            self.send(P.D, {})
            self.send(P.E, {})
    """
    new = project_findings(
        tmp_path,
        {"protocol.py": proto, "hub.py": hub, "hub_shards.py": shards,
         "client.py": client},
        {"GL012"},
    )
    symbols = {f.symbol for f in new}
    # 'd' is handled by the hub but missing from the routing sets;
    # 'e' is routed but the hub has no handler for it
    assert "<topology>.d.unrouted" in symbols, symbols
    assert "<topology>.e.unhandled" in symbols, symbols


GL012_VEC_PROTOCOL = """
SUBMIT_TASKS = "submit_tasks"
"""

GL012_VEC_HUB = """
import protocol as P

class Hub:
    def __init__(self):
        self._handlers = {
            name[len("_on_"):]: getattr(self, name)
            for name in dir(type(self))
            if name.startswith("_on_")
        }

    def _on_submit_tasks(self, conn, p):
        for t in p["tasks"]:
            spec = (t["task_id"], t["args_payload"], t.get("hint"))
            self.admit(spec)
"""


def test_gl012_vector_item_key_missing(tmp_path):
    # bulk frame: the handler loops over payload["tasks"] and reads
    # t["task_id"] / t["args_payload"] on EVERY item; a send site
    # building the item dicts without args_payload must be flagged,
    # and the .get-read "hint" stays optional
    client = """
    import protocol as P

    class Client:
        def go(self, ids):
            payload = {
                "tasks": [
                    {"task_id": i, "hint": 0}
                    for i in ids
                ],
            }
            self.send(P.SUBMIT_TASKS, payload)
    """
    new = project_findings(
        tmp_path,
        {"protocol.py": GL012_VEC_PROTOCOL, "hub.py": GL012_VEC_HUB,
         "client.py": client},
        {"GL012"},
    )
    symbols = {f.symbol for f in new}
    assert any(
        s.endswith(".submit_tasks.tasks[].args_payload.missing")
        for s in symbols
    ), symbols
    assert not any("task_id" in s for s in symbols), symbols
    assert not any("hint" in s for s in symbols), symbols


def test_gl012_vector_clean_when_items_conform(tmp_path):
    client = """
    import protocol as P

    class Client:
        def go(self, ids):
            self.send(P.SUBMIT_TASKS, {
                "tasks": [
                    {"task_id": i, "args_payload": None}
                    for i in ids
                ],
            })
    """
    new = project_findings(
        tmp_path,
        {"protocol.py": GL012_VEC_PROTOCOL, "hub.py": GL012_VEC_HUB,
         "client.py": client},
        {"GL012"},
    )
    assert new == [], [f.render() for f in new]


def test_session_resolves_bulk_submit_vector_contract():
    """The live tree's SUBMIT_TASKS contract must be visible to the
    vector extension end to end: submit_many's item dicts on the send
    side, _on_submit_tasks' per-item reads on the handler side, and
    the message routed in BOTH reactor topologies."""
    from ray_tpu.tools.graftlint.project import session_for

    sess = session_for([PKG_DIR])
    pm = sess.protocol()
    sends = pm.sends_of("submit_tasks")
    assert any(
        "task_id" in s.item_keys.get("tasks", ())
        and "args_payload" in s.item_keys["tasks"]
        for s in sends
    ), [(s.symbol, dict(s.item_keys)) for s in sends]
    hs = pm.handlers_of("submit_tasks")
    assert any(
        {"task_id", "args_kind", "args_payload", "arg_deps", "return_ids"}
        <= set(h.item_required.get("tasks", ()))
        for h in hs
    ), [(h.symbol, dict(h.item_required)) for h in hs]
    hub_tables = [
        t for t in pm.tables if t.kind == "prefix" and t.owner == "Hub"
    ]
    assert hub_tables and "submit_tasks" in hub_tables[0].msgs
    routed = set()
    for r in pm.routing_sets:
        if r.sharded:
            routed |= r.msgs
    assert "submit_tasks" in routed


# --------------------------------------------------------------------- GL013


GL013_PAIR = """
import threading

class ShardRing:
    def push(self, item):
        pass

class Hub:
    def __init__(self):
        self.conns = {}

    def start(self):
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        while True:
            self._handle_disconnect(self.poll())

    def _handle_disconnect(self, conn):
        self.conns.pop(conn, None)

class ReactorShard:
    def __init__(self, hub):
        self.hub = hub
        self._state_ring = ShardRing()

    def run(self):
        while True:
            conn = self.poll()
            self._drop(conn)

    def _drop(self, conn):
        {access}
"""


def test_gl013_rejects_direct_cross_domain_call_but_accepts_ring():
    """The satellite fixture pair: the SAME cross-thread hand-off is
    flagged when made as a direct call into the foreign domain and
    clean when pushed through the sanctioned ring crossing."""
    direct = GL013_PAIR.replace(
        "{access}", "self.hub._handle_disconnect(conn)")
    ring = GL013_PAIR.replace(
        "{access}", 'self._state_ring.push((conn, "conn_lost"))')
    assert "GL013" in codes_of(direct)
    assert "GL013" not in codes_of(ring)


def test_gl013_flags_unlocked_intra_class_cross_thread_state():
    src = """
    import threading

    class Pump:
        def __init__(self):
            self.pending = {}

        def start(self):
            threading.Thread(target=self._reader, daemon=True).start()
            threading.Thread(target=self._writer, daemon=True).start()

        def _reader(self):
            while True:
                self.pending.pop(self.recv(), None)

        def _writer(self):
            while True:
                self.pending[self.next_id()] = 1
    """
    assert "GL013" in codes_of(src)


def test_gl013_accepts_locked_flagged_and_channel_crossings():
    # the same two-thread shape, with every crossing sanctioned: the
    # dict under a lock, a constant-only signal flag, and a queue
    src = """
    import queue
    import threading

    class Pump:
        def __init__(self):
            self.pending = {}
            self._lock = threading.Lock()
            self._running = True
            self._q = queue.Queue()

        def start(self):
            threading.Thread(target=self._reader, daemon=True).start()
            threading.Thread(target=self._writer, daemon=True).start()

        def _reader(self):
            while self._running:
                with self._lock:
                    self.pending.pop(self.recv(), None)
                self._q.put(1)

        def _writer(self):
            while self._running:
                with self._lock:
                    self.pending[self.next_id()] = 1
                self._q.get()

        def stop(self):
            self._running = False
    """
    assert "GL013" not in codes_of(src)


def test_gl013_reads_of_foreign_mutable_state_need_a_lock():
    # a monitor thread reading counters another thread writes — the
    # cross-object *read* arm
    src = """
    import threading

    class Shard:
        def __init__(self):
            self.depth = {}

        def run(self):
            while True:
                self.depth[self.recv()] = 1

    class Monitor:
        def __init__(self, shard):
            self.shard = shard

        def start(self):
            threading.Thread(target=self._scrape, daemon=True).start()

        def _scrape(self):
            while True:
                self.report(self.shard.depth)
    """
    assert "GL013" in codes_of(src)


# --------------------------------------------------------------------- GL014


def test_gl014_flags_nested_lock_order_inversion():
    src = """
    import threading

    class Pool:
        def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

        def one(self):
            with self._alock:
                with self._block:
                    return 1

        def two(self):
            with self._block:
                with self._alock:
                    return 2
    """
    findings = [
        f for f in check_file("x.py", source=textwrap.dedent(src))
        if f.code == "GL014"
    ]
    assert len(findings) == 1
    assert "Pool._alock" in findings[0].message
    assert "Pool._block" in findings[0].message


def test_gl014_clean_with_one_global_order():
    src = """
    import threading

    class Pool:
        def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

        def one(self):
            with self._alock:
                with self._block:
                    return 1

        def two(self):
            with self._alock:
                with self._block:
                    return 2
    """
    assert "GL014" not in codes_of(src)


def test_gl014_sees_cycles_through_method_calls():
    # the inversion hides behind a call: m1 holds left and calls into
    # a method that takes right; m3 holds right and calls one that
    # takes left. Only the transitive closure sees the cycle.
    src = """
    import threading

    class Agent:
        def m1(self):
            with self._left_lock:
                self.m2()

        def m2(self):
            with self._right_lock:
                pass

        def m3(self):
            with self._right_lock:
                self.m4()

        def m4(self):
            with self._left_lock:
                pass
    """
    assert "GL014" in codes_of(src)


def test_gl014_self_nesting_flagged_unless_rlock():
    plain = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def m(self):
            with self._lock:
                with self._lock:
                    pass
    """
    assert "GL014" in codes_of(plain)
    reentrant = plain.replace("threading.Lock()", "threading.RLock()")
    assert "GL014" not in codes_of(reentrant)


# ---------------------------------------------- whole-program revert tests


def test_reverting_node_agent_worker_id_read_is_flagged():
    """The real conformance gap this PR closed: every SPAWN_WORKER send
    shipped a top-level 'worker_id' the node agent never read (it dug
    the id out of the env dict instead) — dead wire weight invisible
    per-file. Re-applying the env-dict read must trip GL012."""
    fresh = live_revert(
        "_private/node_agent.py",
        'self.children[p["worker_id"]] = proc',
        'self.children[p["env"]["RAY_TPU_WORKER_ID"]] = proc',
        codes={"GL012"},
    )
    assert any(
        f.symbol == "<protocol>.spawn_worker.worker_id.never_read"
        for f in fresh
    ), [f.render() for f in fresh]


def test_reverting_shard_direct_disconnect_trips_gl013_too():
    """The documented historical bug behind GL010, re-checked by the
    inferred-ownership pass: the first shard draft called
    hub._handle_disconnect(conn) from the shard thread instead of
    pushing CONN_LOST onto the state ring. GL013 must flag it WITHOUT
    GL010's hand-labelled base names — purely from domain inference."""
    fresh = live_revert(
        "_private/hub_shards.py",
        "self._state_ring.push((conn, None, CONN_LOST, None))",
        "self.hub._handle_disconnect(conn)",
        codes={"GL013"},
    )
    assert any(
        f.code == "GL013" and "_handle_disconnect" in f.symbol
        for f in fresh
    ), [f.render() for f in fresh]


def test_inverting_client_lock_order_is_flagged():
    """The deadlock shape the client's lock discipline prevents:
    _invalidate_resolve touches the resolve cache and the agent pool
    SEQUENTIALLY (drop cache lock, then take pool lock). Nesting the
    two acquisitions — cache->pool in invalidate, pool->cache in
    checkout — is the classic AB/BA inversion; GL014 must flag the
    cycle across the two methods."""
    client_path = os.path.join(PKG_DIR, "_private", "client.py")
    with open(client_path) as f:
        real = f.read()
    reverted = real.replace(
        "        with self._obj_cache_lock:\n"
        "            self._resolve_cache.pop(oid_bytes, None)\n",
        "        with self._obj_cache_lock:\n"
        "            with self._agent_pool_lock:\n"
        "                self._resolve_cache.pop(oid_bytes, None)\n",
    ).replace(
        "        with self._agent_pool_lock:\n"
        "            pool = self._agent_pool.get(endpoint)\n",
        "        with self._agent_pool_lock:\n"
        "            with self._obj_cache_lock:\n"
        "                pool = self._agent_pool.get(endpoint)\n",
    )
    assert reverted != real, "client.py no longer matches the revert"
    fresh, _ = check_paths(
        [PKG_DIR], overrides={client_path: reverted}, codes={"GL014"},
    )
    assert any(
        f.code == "GL014"
        and "_obj_cache_lock" in f.message
        and "_agent_pool_lock" in f.message
        for f in fresh
    ), [f.render() for f in fresh]


# ------------------------------------------------------- analysis session


def test_session_resolves_real_dispatch_tables_and_send_sites():
    """The module-index satellite: the protocol model must find every
    dispatch-table spelling and the batch-frame send site in the REAL
    tree, or the conformance pass is checking a fiction."""
    from ray_tpu.tools.graftlint.project import session_for

    sess = session_for([PKG_DIR])
    pm = sess.protocol()
    assert len(pm.constants) >= 60  # protocol.py is the catalog

    # dict-literal table: CoreClient._inbound_handlers
    dict_tables = [
        t for t in pm.tables if t.kind == "dict" and t.owner == "CoreClient"
    ]
    assert dict_tables, "CoreClient dict table not resolved"
    assert {"reply", "pubsub_msg", "cancel_task", "ready_push"} <= set(
        dict_tables[0].msgs
    )

    # dir()/_on_ convention table: Hub._handlers
    hub_tables = [
        t for t in pm.tables if t.kind == "prefix" and t.owner == "Hub"
    ]
    assert hub_tables and len(hub_tables[0].msgs) >= 40
    assert "submit_task" in hub_tables[0].msgs

    # if/elif chains: the node agent's _handle
    elif_owners = {t.owner for t in pm.tables if t.kind == "elif"}
    assert any("_handle" in o for o in elif_owners), elif_owners

    # batch-frame send site: release_owned rides the client send buffer
    batch = [s for s in pm.sends if s.msg == "release_owned"]
    assert batch, "release_owned batch-append send site not resolved"
    assert batch[0].via == "append"
    assert batch[0].keys is not None and "object_ids" in batch[0].keys

    # sharded routing sets mirror hub_shards.SERVICE_OF inputs
    routed = set()
    for r in pm.routing_sets:
        if r.sharded:
            routed |= r.msgs
    assert {"submit_task", "put", "subscribe"} <= routed

    # inline request/response comparisons count as consumption
    assert "obj_data" in pm.compared and "obj_put_ok" in pm.compared


def test_thread_model_seeds_the_documented_entry_points():
    from ray_tpu.tools.graftlint.project import session_for

    sess = session_for([PKG_DIR])
    tm = sess.threads()
    shard = tm.resolve("ReactorShard")
    assert any("ReactorShard.run" in d for d in shard.domains.get("run", ()))
    client = tm.resolve("CoreClient")
    assert any(
        "_read_loop" in d for d in client.domains.get("_read_loop", ())
    )
    # dispatch-table handlers inherit their dispatcher's domain: the
    # client's _on_reply runs wherever the reader loop runs
    reply_domains = client.domains.get("_on_reply") or set()
    assert reply_domains & (client.domains.get("_read_loop") or set())


# ------------------------------------------------------------- parse cache


def test_parse_cache_one_parse_per_file_and_no_rescan_regression():
    """The perf satellite: all 17 checkers (11 per-file + 6 whole-
    program) share ONE parse of each file, a second full-tree run
    re-parses nothing, and the cached run is not slower than the
    parse-paying run despite the added whole-program passes."""
    import time as _time

    from ray_tpu.tools.graftlint.core import (
        _PARSE_CACHE,
        iter_python_files,
        parse_stats,
    )

    _PARSE_CACHE.clear()
    n_files = sum(1 for _ in iter_python_files([PKG_DIR]))
    assert n_files > 100

    p0 = parse_stats["parses"]
    t0 = _time.monotonic()
    check_paths([PKG_DIR])
    t_cold = _time.monotonic() - t0
    assert parse_stats["parses"] - p0 == n_files

    p1 = parse_stats["parses"]
    h1 = parse_stats["hits"]
    t0 = _time.monotonic()
    check_paths([PKG_DIR])
    t_warm = _time.monotonic() - t0
    assert parse_stats["parses"] == p1, "warm run re-parsed files"
    assert parse_stats["hits"] - h1 == n_files
    # the cache must actually pay: a full 17-checker warm run beats the
    # cold run that had to parse (1.1 slack absorbs box noise)
    assert t_warm < t_cold * 1.1, (t_cold, t_warm)
    # absolute backstop so a pathological whole-program blowup fails
    # loudly even if both runs regress together
    assert t_cold < 60, t_cold


# ------------------------------------------------------ json / changed-only


def test_cli_json_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def fire(actor):\n    actor.ping.remote()\n")
    r = run_cli(bad, "--format", "json")
    assert r.returncode == 1
    data = json.loads(r.stdout)
    assert data["baselined"] == 0 and data["changed_only"] is False
    assert [f["code"] for f in data["findings"]] == ["GL004"]
    assert data["findings"][0]["path"] == str(bad)
    assert data["findings"][0]["line"] == 2

    good = tmp_path / "good.py"
    good.write_text("def add(a, b):\n    return a + b\n")
    r = run_cli(good, "--format", "json")
    assert r.returncode == 0
    assert json.loads(r.stdout)["findings"] == []


def test_cli_changed_only_scopes_reporting_to_the_git_diff(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*cmd):
        r = subprocess.run(
            ["git", "-C", str(repo), "-c", "user.email=t@t",
             "-c", "user.name=t", *cmd],
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        return r.stdout

    git("init", "-q")
    committed = repo / "committed.py"
    committed.write_text("def fire(actor):\n    actor.ping.remote()\n")
    git("add", "committed.py")
    git("commit", "-qm", "seed")

    # an untracked file with a fresh bug
    fresh = repo / "fresh.py"
    fresh.write_text("def fire(actor):\n    actor.ping.remote()\n")

    r = run_cli(repo, "--changed-only", "--format", "json", cwd=repo)
    assert r.returncode == 1, r.stdout + r.stderr
    data = json.loads(r.stdout)
    paths = {f["path"] for f in data["findings"]}
    # the committed bug is invisible in changed-only mode; the fresh
    # file's finding is reported
    assert paths == {str(fresh)}, paths
    assert data["changed_only"] is True

    # once everything is committed the diff is empty: exit 0, nothing
    # reported (the committed bug still exists — full runs see it)
    git("add", "fresh.py")
    git("commit", "-qm", "fresh")
    r = run_cli(repo, "--changed-only", "--format", "json", cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout)["findings"] == []

    r = run_cli(repo, cwd=repo)
    assert r.returncode == 1  # full run still reports both


def test_gl013_bare_annotation_is_not_a_write():
    # `self.pending: dict` declares without assigning; treating it as a
    # write fabricated cross-thread conflicts
    src = """
    import threading

    class Pump:
        def start(self):
            threading.Thread(target=self._reader, daemon=True).start()
            threading.Thread(target=self._writer, daemon=True).start()

        def _reader(self):
            while True:
                self.pending: dict
                self.consume(self.pending)

        def _writer(self):
            while True:
                self.report(len(self.pending))
    """
    assert "GL013" not in codes_of(src)


def test_same_named_classes_in_different_modules_both_analyzed(tmp_path):
    # the thread/lock models key by (module, class): a second class
    # carrying an already-seen name must not be silently dropped, and
    # its same-named locks are DIFFERENT locks (no phantom cycles)
    a = """
    import threading

    class Backend:
        def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

        def m(self):
            with self._alock:
                with self._block:
                    pass
    """
    b = """
    import threading

    class Backend:
        def __init__(self):
            self._alock = threading.Lock()
            self._block = threading.Lock()

        def m(self):
            with self._block:
                with self._alock:
                    pass
    """
    # opposite nesting orders, but in two DIFFERENT classes that merely
    # share a name: no shared lock, no cycle
    new = project_findings(
        tmp_path, {"mod_a.py": a, "mod_b.py": b}, {"GL014"})
    assert new == [], [f.render() for f in new]
    # ...and GL013 still analyzes BOTH same-named classes: give the
    # second a real cross-thread bug and it must be flagged even
    # though a clean class with the same name was indexed first
    buggy = """
    import threading

    class Backend:
        def start(self):
            threading.Thread(target=self._reader, daemon=True).start()
            threading.Thread(target=self._writer, daemon=True).start()

        def _reader(self):
            while True:
                self.pending.pop(self.recv(), None)

        def _writer(self):
            while True:
                self.pending[self.next_id()] = 1
    """
    new2 = project_findings(
        tmp_path, {"mod_a.py": a, "mod_c.py": buggy}, {"GL013"})
    assert any(f.code == "GL013" and f.path.endswith("mod_c.py")
               for f in new2), [f.render() for f in new2]


def test_changed_only_keeps_whole_program_findings(tmp_path):
    # deleting a handler anchors the sent-but-unhandled finding at the
    # UNCHANGED send site; report_only must not filter it away
    d = tmp_path / "proj2"
    d.mkdir()
    (d / "protocol.py").write_text("PING = \"ping\"\n")
    (d / "client.py").write_text(textwrap.dedent("""
    import protocol as P

    class Client:
        def go(self):
            self.send(P.PING, {})
    """))
    hub = d / "hub.py"
    hub.write_text(textwrap.dedent("""
    import protocol as P
    """))
    # pretend only hub.py changed (the handler was deleted from it):
    # the GL012 finding anchors in client.py yet must still be reported
    new, _ = check_paths(
        [str(d)], codes={"GL012"}, report_only={str(hub)},
    )
    assert any(
        f.symbol == "<protocol>.ping.unhandled" for f in new
    ), [f.render() for f in new]
    # ...while per-file findings outside the changed set stay scoped
    (d / "extra.py").write_text(
        "def fire(actor):\n    actor.ping.remote()\n")
    new2, _ = check_paths(
        [str(d)], codes={"GL004", "GL012"}, report_only={str(hub)},
    )
    assert not any(f.code == "GL004" for f in new2)


# --------------------------------------------------------------------- GL015
#
# Async discipline is a whole-program property: the coroutine that
# stalls the loop never says `sleep` itself — a sync helper two calls
# away does. All fixtures run through the session (project_findings).


GL015_TRANSITIVE = """
import asyncio
import time


def _backoff():
    time.sleep(0.5)


def _retry():
    _backoff()


class Server:
    async def handle(self, req):
        _retry()
        return req
"""


def test_gl015_flags_transitively_blocking_sync_helper(tmp_path):
    fresh = project_findings(tmp_path, {"app.py": GL015_TRANSITIVE},
                             codes={"GL015"})
    hits = [f for f in fresh if f.symbol.endswith("._retry.blocking")]
    assert hits, [f.render() for f in fresh]
    # the message names the whole chain, not just the first hop
    assert "_backoff" in hits[0].message and "time.sleep" in hits[0].message


def test_gl015_blocking_root_crosses_modules(tmp_path):
    # the helper lives in another module and parks on a no-timeout
    # future (GL003's method-form table seeds the roots)
    fresh = project_findings(tmp_path, {
        "pool.py": """
        from concurrent.futures import ThreadPoolExecutor

        _POOL = ThreadPoolExecutor(2)

        def run_sync(fn):
            fut = _POOL.submit(fn)
            return fut.result()
        """,
        "app.py": """
        from pool import run_sync

        class Server:
            async def handle(self, req):
                return run_sync(req)
        """,
    }, codes={"GL015"})
    assert any(
        f.symbol == "Server.handle.pool.run_sync.blocking" for f in fresh
    ), [f.render() for f in fresh]


def test_gl015_clean_when_helper_runs_in_executor(tmp_path):
    fresh = project_findings(tmp_path, {"app.py": """
    import asyncio
    import time


    def _backoff():
        time.sleep(0.5)


    class Server:
        async def handle(self, req):
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, _backoff)
            return req
    """}, codes={"GL015"})
    assert fresh == [], [f.render() for f in fresh]


def test_gl015_flags_lock_shared_with_slow_thread(tmp_path):
    # the sync helper never blocks — but it takes a lock a worker
    # thread holds around time.sleep, so the loop can stall for the
    # holder's whole window
    fresh = project_findings(tmp_path, {"mixed.py": """
    import threading
    import time


    class Mixed:
        def __init__(self):
            self._lock = threading.Lock()
            threading.Thread(target=self._worker, daemon=True).start()

        def _worker(self):
            while True:
                with self._lock:
                    time.sleep(1.0)

        def _peek(self):
            with self._lock:
                return 1

        async def view(self):
            return self._peek()
    """}, codes={"GL015"})
    hits = [f for f in fresh if f.symbol.endswith("._peek.blocking")]
    assert hits, [f.render() for f in fresh]
    assert "_lock" in hits[0].message


def test_gl015_flags_never_awaited_coroutine(tmp_path):
    fresh = project_findings(tmp_path, {"app.py": """
    class Server:
        async def _notify(self):
            pass

        async def handle(self):
            self._notify()
    """}, codes={"GL015"})
    assert any(
        f.symbol.endswith("._notify.never_awaited") for f in fresh
    ), [f.render() for f in fresh]


def test_gl015_awaited_or_stored_coroutines_are_clean(tmp_path):
    fresh = project_findings(tmp_path, {"app.py": """
    import asyncio


    class Server:
        async def _notify(self):
            pass

        async def handle(self):
            await self._notify()
            task = asyncio.create_task(self._notify())
            return task
    """}, codes={"GL015"})
    assert fresh == [], [f.render() for f in fresh]


GL015_CTX_DROP = """
import asyncio
from ray_tpu.util import tracing as _tracing


class Proxy:
    async def handle(self, req, handle):
        tr = _tracing.current_context()
        loop = asyncio.get_running_loop()

        def _routed():
            return handle.remote(req).result()

        return await loop.run_in_executor(None, _routed)
"""


def test_gl015_flags_context_dropping_dispatch(tmp_path):
    fresh = project_findings(tmp_path, {"proxy.py": GL015_CTX_DROP},
                             codes={"GL015"})
    assert any(
        f.symbol == "Proxy.handle._routed.ctx_dropped" for f in fresh
    ), [f.render() for f in fresh]


def test_gl015_ctx_repush_and_none_guard_are_clean(tmp_path):
    # PR 13's shipped shape: re-push inside the closure; the no-trace
    # fast path under `if tr is None:` has nothing to propagate
    fresh = project_findings(tmp_path, {"proxy.py": """
    import asyncio
    from ray_tpu.util import tracing as _tracing


    class Proxy:
        async def handle(self, req, handle):
            tr = _tracing.current_context()
            loop = asyncio.get_running_loop()
            if tr is None:
                return await loop.run_in_executor(
                    None, lambda: handle.remote(req).result()
                )

            def _routed():
                token = _tracing.push_context(tr)
                try:
                    return handle.remote(req).result()
                finally:
                    _tracing.pop_context(token)

            return await loop.run_in_executor(None, _routed)
    """}, codes={"GL015"})
    assert fresh == [], [f.render() for f in fresh]


def test_reverting_proxy_context_repush_is_flagged():
    """PR 13's hand-fix as a permanent rule: the proxy's sticky-routing
    closure re-pushes the ambient trace context before running on the
    executor thread. Stripping the re-push from the REAL proxy.py must
    trip GL015's ctx_dropped arm."""
    fresh = live_revert(
        "serve/_private/proxy.py",
        "                def _routed():\n"
        "                    token = _tracing.push_context((tr[0], proxy_sid))\n"
        "                    try:\n"
        "                        return handle.remote(req).result()\n"
        "                    finally:\n"
        "                        _tracing.pop_context(token)\n",
        "                def _routed():\n"
        "                    return handle.remote(req).result()\n",
        codes={"GL015"},
    )
    assert any(
        f.symbol == "HTTPProxy._handle._routed.ctx_dropped" for f in fresh
    ), [f.render() for f in fresh]


# --------------------------------------------------------------------- GL016
#
# Resource lifecycle: leaks are invisible per-file because ownership
# legitimately moves around — into registries, out via returns. The
# escape analysis has to see the whole function; the class layer the
# whole class.


def test_gl016_flags_handle_never_released(tmp_path):
    fresh = project_findings(tmp_path, {"store.py": """
    import mmap


    def leak(n):
        seg = mmap.mmap(-1, n)
        return n
    """}, codes={"GL016"})
    assert any(
        f.symbol == "leak.seg.unreleased" for f in fresh
    ), [f.render() for f in fresh]


def test_gl016_flags_raising_call_between_acquire_and_release(tmp_path):
    fresh = project_findings(tmp_path, {"store.py": """
    import mmap


    def risky(fd, n, meta):
        seg = mmap.mmap(fd, n)
        validate(meta)
        seg.close()


    def validate(meta):
        if not meta:
            raise ValueError(meta)
    """}, codes={"GL016"})
    assert any(
        f.symbol == "risky.seg.leak_on_raise" for f in fresh
    ), [f.render() for f in fresh]


def test_gl016_release_transfer_and_tryfinally_are_clean(tmp_path):
    # every sanctioned resolution: close in finally, store into a
    # tracked registry (with a drop path), return to caller, context
    # manager, hand-off to another call
    fresh = project_findings(tmp_path, {"store.py": """
    import mmap


    class Store:
        def __init__(self):
            self._segments = {}

        def put(self, name, fd, n, meta):
            seg = mmap.mmap(fd, n)
            try:
                validate(meta)
            except ValueError:
                seg.close()
                raise
            self._segments[name] = seg

        def drop(self, name):
            seg = self._segments.pop(name, None)
            if seg is not None:
                seg.close()


    def guarded(fd, n, meta):
        seg = mmap.mmap(fd, n)
        try:
            validate(meta)
        finally:
            seg.close()


    def handoff(fd, n):
        seg = mmap.mmap(fd, n)
        return seg


    def scoped(path):
        with open(path) as f:
            return f.read()


    def validate(meta):
        if not meta:
            raise ValueError(meta)
    """}, codes={"GL016"})
    assert fresh == [], [f.render() for f in fresh]


def test_gl016_flags_selector_without_unregister(tmp_path):
    fresh = project_findings(tmp_path, {"reactor.py": """
    import selectors


    class Reactor:
        def start(self, sock):
            self._sel = selectors.DefaultSelector()
            self._sel.register(sock, selectors.EVENT_READ)
    """}, codes={"GL016"})
    symbols = {f.symbol for f in fresh}
    assert "reactor.Reactor.selector.unregister_missing" in symbols, symbols
    assert "reactor.Reactor.selector.close_missing" in symbols, symbols


def test_gl016_selector_with_full_lifecycle_is_clean(tmp_path):
    fresh = project_findings(tmp_path, {"reactor.py": """
    import selectors


    class Reactor:
        def start(self, sock):
            self._sel = selectors.DefaultSelector()
            self._sel.register(sock, selectors.EVENT_READ)

        def drop(self, sock):
            sel = self._sel
            sel.unregister(sock)

        def stop(self):
            self._sel.close()
    """}, codes={"GL016"})
    assert fresh == [], [f.render() for f in fresh]


def test_gl016_flags_timers_without_teardown_clear(tmp_path):
    src = """
    import heapq


    class Hub:
        def __init__(self):
            self.timers = []

        def _add_timer(self, deadline, cb):
            heapq.heappush(self.timers, (deadline, cb))
    {teardown}
    """
    fresh = project_findings(tmp_path, {
        "hub.py": src.format(teardown=""),
    }, codes={"GL016"})
    assert any(
        f.symbol == "hub.Hub.timers.teardown_clear_missing" for f in fresh
    ), [f.render() for f in fresh]

    fresh = project_findings(tmp_path, {
        "hub.py": src.format(teardown="""
        def teardown(self):
            self.timers.clear()"""),
    }, codes={"GL016"})
    assert fresh == [], [f.render() for f in fresh]


def test_gl016_flags_registry_without_drop_path(tmp_path):
    fresh = project_findings(tmp_path, {"store.py": """
    import mmap


    class Store:
        def __init__(self):
            self._segments = {}

        def put(self, name, fd, n):
            seg = mmap.mmap(fd, n)
            self._segments[name] = seg
    """}, codes={"GL016"})
    assert any(
        f.symbol == "store.Store._segments.drop_missing" for f in fresh
    ), [f.render() for f in fresh]


def test_gl016_flags_span_record_never_emitted(tmp_path):
    # span open/emit pairing rides the same escape analysis: a record
    # built and dropped never reaches the collector
    fresh = project_findings(tmp_path, {"obs.py": """
    def make_runtime_record(kind):
        return {"kind": kind}


    def _emit(record):
        pass


    def bad(kind):
        rec = make_runtime_record(kind)
        return 1


    def good(kind):
        rec = make_runtime_record(kind)
        _emit(rec)
    """}, codes={"GL016"})
    symbols = {f.symbol for f in fresh}
    assert "bad.rec.unreleased" in symbols, symbols
    assert not any(s.startswith("good.") for s in symbols), symbols


def test_reverting_hub_disconnect_unregister_is_flagged():
    """The real lifecycle the class layer guards: hub's disconnect path
    unregisters the dead conn from the selector. Replacing that call
    with `pass` in the REAL hub.py leaves registration with no
    unregister anywhere in the class — GL016 must flag it."""
    fresh = live_revert(
        "_private/hub.py",
        "sel.unregister(conn)",
        "pass",
        codes={"GL016"},
    )
    assert any(
        f.symbol == "hub.Hub.selector.unregister_missing" for f in fresh
    ), [f.render() for f in fresh]


def test_gl016_resource_model_resolves_real_acquire_sites():
    """Satellite: the model must keep tracking the three live acquire
    families this rule exists for — hub's selector + one-shot timers,
    the shard reactor's selector, and the object store's mapping table
    (stores AND drops). A refactor that renames these out from under
    the model silently disables the rule; this pins the resolution."""
    from ray_tpu.tools.graftlint.core import iter_python_files, parse_cached
    from ray_tpu.tools.graftlint.project import ProjectSession

    ctxs = [parse_cached(p) for p in iter_python_files([PKG_DIR])]
    rm = ProjectSession([c for c in ctxs if c is not None]).resources()

    hub = rm.classes["hub.Hub"]
    assert hub.register_sites and hub.unregister_sites
    assert hub.selector_close_sites
    assert "timers" in hub.timer_attrs
    assert "timers" in hub.timer_clears  # the _teardown_runtime clear

    shard = rm.classes["hub_shards.ReactorShard"]
    assert shard.register_sites and shard.unregister_sites

    store = rm.classes["object_store.ShmObjectStore"]
    assert "_segments" in store.registry_attrs  # mapping table stores
    assert "_segments" in store.registry_drops  # drop_mapping/free


# --------------------------------------------------------------------- GL017
#
# Deadline conformance is path-scoped: the contract only binds the
# serve plane, so fixtures materialize a ray_tpu/serve/ subtree.


GL017_LITERALS = """
import asyncio


class Handle:
    def fetch(self, fut, evt, q):
        fut.result(timeout=30.0)
        evt.wait(5)
        q.get(timeout=2.0)

    async def awaited(self, coro):
        return await asyncio.wait_for(coro, 10.0)
"""


def test_gl017_flags_literal_timeouts_in_serve(tmp_path):
    fresh = project_findings(
        tmp_path, {"ray_tpu/serve/app.py": GL017_LITERALS},
        codes={"GL017"},
    )
    symbols = {f.symbol for f in fresh}
    assert symbols == {
        "Handle.fetch.result.literal_timeout",
        "Handle.fetch.wait.literal_timeout",
        "Handle.fetch.get.literal_timeout",
        "Handle.awaited.wait_for.literal_timeout",
    }, symbols


def test_gl017_derived_zero_and_dict_get_are_clean(tmp_path):
    fresh = project_findings(tmp_path, {"ray_tpu/serve/app.py": """
    import asyncio


    class Handle:
        def fetch(self, fut, meta, cfg):
            remaining = meta.remaining_s()
            fut.result(timeout=remaining)
            return cfg.get("retries", 5)

        def poll(self, evt):
            return evt.wait(timeout=0)

        async def awaited(self, coro, meta):
            return await asyncio.wait_for(coro, meta.remaining_s())
    """}, codes={"GL017"})
    assert fresh == [], [f.render() for f in fresh]


def test_gl017_is_scoped_to_the_serve_plane(tmp_path):
    # the identical source outside ray_tpu/serve/ is out of contract
    fresh = project_findings(
        tmp_path, {"ray_tpu/_private/other.py": GL017_LITERALS},
        codes={"GL017"},
    )
    assert fresh == [], [f.render() for f in fresh]


def test_reverting_handle_deadline_derivation_is_flagged():
    """PR 15's deadline contract: the response-await path computes its
    wait_for bound from the request deadline. Hard-coding the literal
    30s back into the REAL handle.py must trip GL017."""
    fresh = live_revert(
        "serve/handle.py",
        "timeout=remaining",
        "timeout=30.0",
        codes={"GL017"},
    )
    assert any(
        f.symbol == "DeploymentResponse.__await__._get.wait_for"
                    ".literal_timeout"
        for f in fresh
    ), [f.render() for f in fresh]


# --------------------------------------------------------------------- sarif


def test_cli_sarif_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def fire(actor):\n    actor.ping.remote()\n")
    r = run_cli(bad, "--format", "sarif")
    assert r.returncode == 1
    log = json.loads(r.stdout)
    assert log["version"] == "2.1.0"
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    assert [rule["id"] for rule in run["tool"]["driver"]["rules"]] == [
        "GL004"
    ]
    res = run["results"][0]
    assert res["ruleId"] == "GL004"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad.py")
    assert loc["region"]["startLine"] == 2
    # the fingerprint carries the baseline identity, so an uploader
    # dedupes across pushes the same way the baseline would
    assert res["partialFingerprints"]["graftlint/v1"].endswith(
        ":GL004:fire.discarded"
    )

    good = tmp_path / "good.py"
    good.write_text("def add(a, b):\n    return a + b\n")
    r = run_cli(good, "--format", "sarif")
    assert r.returncode == 0
    assert json.loads(r.stdout)["runs"][0]["results"] == []


def test_cli_sarif_composes_with_changed_only(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*cmd):
        r = subprocess.run(
            ["git", "-C", str(repo), "-c", "user.email=t@t",
             "-c", "user.name=t", *cmd],
            capture_output=True, text=True,
        )
        assert r.returncode == 0, r.stderr
        return r.stdout

    git("init", "-q")
    committed = repo / "committed.py"
    committed.write_text("def fire(actor):\n    actor.ping.remote()\n")
    git("add", "committed.py")
    git("commit", "-qm", "seed")
    fresh = repo / "fresh.py"
    fresh.write_text("def fire(actor):\n    actor.ping.remote()\n")

    r = run_cli(repo, "--changed-only", "--format", "sarif", cwd=repo)
    assert r.returncode == 1, r.stdout + r.stderr
    results = json.loads(r.stdout)["runs"][0]["results"]
    uris = {
        res["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
        for res in results
    }
    # only the uncommitted file's finding is annotated
    assert uris == {str(fresh)}, uris
