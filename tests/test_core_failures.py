"""Failure handling: worker crashes, retries, cancellation.

Modeled on the reference's tests/test_failure.py + test_actor_failures.py
kill-process patterns (python/ray/_private/test_utils.py:572).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError, TaskError, WorkerCrashedError


def test_task_retry_on_worker_death(ray_start_regular):
    @ray_tpu.remote(max_retries=2)
    def flaky(marker_dir):
        # die the first time, succeed on retry
        marker = os.path.join(marker_dir, "ran_once")
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)
        return "recovered"

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        assert ray_tpu.get(flaky.remote(d), timeout=60) == "recovered"


def test_no_retry_exhausted(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=60)


def test_cancel_pending_task(ray_start_regular):
    @ray_tpu.remote
    def blocker():
        time.sleep(30)

    @ray_tpu.remote
    def target():
        return 1

    # fill both CPUs, then queue a task and cancel it while pending
    b1, b2 = blocker.remote(), blocker.remote()
    time.sleep(0.5)
    t = target.remote()
    ray_tpu.cancel(t)
    with pytest.raises((TaskCancelledError, TaskError)):
        ray_tpu.get(t, timeout=10)


def test_application_error_not_retried(ray_start_regular):
    calls_file = "/tmp/ray_tpu_test_calls_%d" % os.getpid()
    if os.path.exists(calls_file):
        os.unlink(calls_file)

    @ray_tpu.remote(max_retries=3)
    def app_error():
        with open(calls_file, "a") as f:
            f.write("x")
        raise ValueError("app error")

    with pytest.raises(TaskError):
        ray_tpu.get(app_error.remote())
    # application errors are not retried (only worker crashes are)
    assert os.path.getsize(calls_file) == 1
    os.unlink(calls_file)
