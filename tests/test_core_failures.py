"""Failure handling: worker crashes, retries, cancellation.

Modeled on the reference's tests/test_failure.py + test_actor_failures.py
kill-process patterns (python/ray/_private/test_utils.py:572).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError, TaskError, WorkerCrashedError


def test_task_retry_on_worker_death(ray_start_regular):
    @ray_tpu.remote(max_retries=2)
    def flaky(marker_dir):
        # die the first time, succeed on retry
        marker = os.path.join(marker_dir, "ran_once")
        if not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)
        return "recovered"

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        assert ray_tpu.get(flaky.remote(d), timeout=60) == "recovered"


def test_no_retry_exhausted(ray_start_regular):
    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    with pytest.raises(WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=60)


def test_cancel_pending_task(ray_start_regular):
    @ray_tpu.remote
    def blocker():
        time.sleep(30)

    @ray_tpu.remote
    def target():
        return 1

    # fill both CPUs, then queue a task and cancel it while pending
    b1, b2 = blocker.remote(), blocker.remote()
    time.sleep(0.5)
    t = target.remote()
    ray_tpu.cancel(t)
    with pytest.raises((TaskCancelledError, TaskError)):
        ray_tpu.get(t, timeout=10)


def test_force_cancel_running_task(ray_start_regular):
    """force=True kills the executing worker; the ref resolves to
    TaskCancelledError and the task is NOT retried."""

    @ray_tpu.remote(max_retries=3)
    def spin(path):
        open(path, "a").write("x")
        time.sleep(60)

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "started")
        ref = spin.remote(marker)
        deadline = time.time() + 20
        while not os.path.exists(marker) and time.time() < deadline:
            time.sleep(0.05)
        assert os.path.exists(marker)
        ray_tpu.cancel(ref, force=True)
        with pytest.raises(TaskCancelledError):
            ray_tpu.get(ref, timeout=30)
        # not retried despite max_retries=3
        time.sleep(1.0)
        assert open(marker).read() == "x"


def test_cooperative_cancel_running_task(ray_start_regular):
    """force=False interrupts the worker with SIGINT (KeyboardInterrupt
    inside the task) — worker survives and serves again."""

    @ray_tpu.remote
    def spin(path):
        open(path, "a").write("x")
        time.sleep(60)

    @ray_tpu.remote
    def ping():
        return "alive"

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "started")
        ref = spin.remote(marker)
        deadline = time.time() + 20
        while not os.path.exists(marker) and time.time() < deadline:
            time.sleep(0.05)
        ray_tpu.cancel(ref, force=False)
        with pytest.raises(TaskCancelledError):
            ray_tpu.get(ref, timeout=30)
        assert ray_tpu.get(ping.remote(), timeout=30) == "alive"


def test_cancel_queued_actor_call(ray_start_regular):
    @ray_tpu.remote
    class Slow:
        def block(self):
            time.sleep(20)
            return "blocked"

        def quick(self):
            return "quick"

    a = Slow.remote()
    ray_tpu.get(a.quick.remote(), timeout=30)  # actor alive
    r1 = a.block.remote()
    time.sleep(0.3)
    r2 = a.quick.remote()  # queued behind block()
    ray_tpu.cancel(r2)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(r2, timeout=10)
    ray_tpu.kill(a)


def test_actor_restart_on_crash(ray_start_regular):
    @ray_tpu.remote(max_restarts=2)
    class Fragile:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def crash(self):
            os._exit(1)

    a = Fragile.remote()
    assert ray_tpu.get(a.bump.remote(), timeout=30) == 1
    crash_ref = a.crash.remote()
    with pytest.raises(Exception):
        ray_tpu.get(crash_ref, timeout=30)
    # restarted incarnation: state reset, still serving
    deadline = time.time() + 30
    val = None
    while time.time() < deadline:
        try:
            val = ray_tpu.get(a.bump.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.2)
    assert val == 1
    ray_tpu.kill(a)


def test_application_error_not_retried(ray_start_regular):
    calls_file = "/tmp/ray_tpu_test_calls_%d" % os.getpid()
    if os.path.exists(calls_file):
        os.unlink(calls_file)

    @ray_tpu.remote(max_retries=3)
    def app_error():
        with open(calls_file, "a") as f:
            f.write("x")
        raise ValueError("app error")

    with pytest.raises(TaskError):
        ray_tpu.get(app_error.remote())
    # application errors are not retried (only worker crashes are)
    assert os.path.getsize(calls_file) == 1
    os.unlink(calls_file)


def test_retry_exceptions_true(ray_start_regular):
    """retry_exceptions=True retries application errors (reference:
    @ray.remote(retry_exceptions=True))."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "tries")

        @ray_tpu.remote(max_retries=3, retry_exceptions=True)
        def flaky_app():
            with open(marker, "a") as f:
                f.write("x")
            if os.path.getsize(marker) < 3:
                raise ValueError("transient")
            return "ok"

        assert ray_tpu.get(flaky_app.remote(), timeout=60) == "ok"
        assert os.path.getsize(marker) == 3


def test_retry_exceptions_type_filter(ray_start_regular):
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        marker = os.path.join(d, "tries")

        @ray_tpu.remote(max_retries=3, retry_exceptions=[KeyError])
        def wrong_type():
            with open(marker, "a") as f:
                f.write("x")
            raise ValueError("not retryable")

        with pytest.raises(TaskError):
            ray_tpu.get(wrong_type.remote(), timeout=60)
        assert os.path.getsize(marker) == 1  # ValueError not in the list


# ------------------------------------------- hub disconnect hardening


def _bare_hub(tmp_path):
    from ray_tpu._private.hub import Hub

    return Hub(session_dir=str(tmp_path / "session"), resources={"CPU": 1})


def test_disconnect_with_failed_put_tombstone(tmp_path):
    """Regression: a client that dies mid-chunked-put after its stream
    was poisoned leaves a ('failed', msg) tombstone in _client_puts.
    The disconnect cleanup used to call .name on it (AttributeError)
    and kill the hub reactor thread."""
    hub = _bare_hub(tmp_path)
    try:
        conn = object()
        objdir = os.path.join(hub.session_dir, "objects")
        os.makedirs(objdir, exist_ok=True)
        live = open(os.path.join(objdir, ".client.live.seg"), "wb")
        hub._client_puts[(id(conn), "poisoned")] = ("failed", "disk full")
        hub._client_puts[(id(conn), "live")] = live
        hub._handle_disconnect(conn)  # must not raise
        assert not [k for k in hub._client_puts if k[0] == id(conn)]
        assert live.closed
        assert not os.path.exists(live.name)
    finally:
        hub.listener.close()


def test_safe_disconnect_never_raises(tmp_path):
    """_safe_disconnect is the reactor's last line of defense: even a
    cleanup bug must cost one connection, not the hub thread."""
    hub = _bare_hub(tmp_path)
    try:
        class Boom:
            # id() collides with nothing; make the cleanup itself blow
            pass

        conn = Boom()
        hub.conn_to_worker[conn] = "w-missing"
        hub.workers.clear()

        def exploding(_conn):
            raise RuntimeError("cleanup bug")

        hub._handle_disconnect = exploding
        hub._safe_disconnect(conn)  # swallowed + logged, not raised
    finally:
        hub.listener.close()
