"""bench_core.py harness smoke test (tier-1 safe, not marked slow).

Runs one --smoke micro-iteration of the core microbenchmark end to end
and asserts the --json report covers every BASELINES metric — so a
refactor that silently drops a benchmark row (or breaks the harness
against a runtime change) fails CI instead of being discovered at the
next perf PR. Numbers are NOT checked: smoke iteration counts are
sized for latency, not measurement.
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench_core.py")


def test_smoke_run_reports_every_baseline_metric(tmp_path):
    out_path = tmp_path / "bench.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT)
    r = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--trials", "2",
         "--json", str(out_path)],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=420,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"

    data = json.loads(out_path.read_text())
    assert data["mode"] == "smoke"
    # --trials N schema: median value + the per-trial samples per row
    assert data["trials"] == 2
    for name, rec in data["metrics"].items():
        trials = rec.get("trials")
        if trials is not None:  # rows measured through timeit()
            assert len(trials) == 2, name
            # value is the 2-decimal-rounded median, trials are rounded
            # at 3 decimals: compare with rounding slack, or two close
            # samples near a 0.01 boundary flake the gate
            assert (
                min(trials) - 0.01 <= rec["value"] <= max(trials) + 0.01
            ), (name, rec["value"], trials)

    sys.path.insert(0, REPO_ROOT)
    try:
        from bench_core import BASELINES
    finally:
        sys.path.remove(REPO_ROOT)

    missing = set(BASELINES) - set(data["metrics"])
    assert not missing, f"BASELINES metrics missing from report: {missing}"
    # platform stamping (PR 18): the run-level platform plus one stamp
    # per row; on the baseline platform vs_baseline must be computed
    # for every BASELINES row, and report() refuses the ratio anywhere
    # else — a cross-platform geomean must be impossible to emit
    from bench_core import BASELINE_PLATFORM

    assert data["platform"] == BASELINE_PLATFORM  # JAX_PLATFORMS=cpu above
    for name, rec in data["metrics"].items():
        assert rec.get("platform"), f"{name} row missing platform stamp"
        if rec["platform"] != BASELINE_PLATFORM:
            assert rec["vs_baseline"] is None, name
        elif name in BASELINES:
            assert rec["vs_baseline"] is not None, name
    # tracing_overhead schema: the on/off throughput ratio with runtime
    # tracing head-sampled at 1.0 (evidence row, never gated)
    overhead = data["metrics"]["tracing_overhead"]
    assert overhead["unit"] == "ratio"
    assert overhead["value"] > 0
    for name, rec in data["metrics"].items():
        assert rec["value"] > 0, f"{name} reported a non-positive value"
    # every stdout metric line is one JSON object (the scrapeable form)
    parsed = [
        json.loads(line) for line in r.stdout.splitlines()
        if line.startswith("{")
    ]
    assert {p["metric"] for p in parsed} >= set(BASELINES)


def test_report_refuses_cross_platform_ratio(monkeypatch):
    """A row measured on non-baseline hardware gets its platform
    stamped and its vs_baseline refused (None) — cpu-box baselines are
    not comparable to tpu/gpu numbers."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench_core
    finally:
        sys.path.remove(REPO_ROOT)

    monkeypatch.setattr(bench_core, "RESULTS", [])
    monkeypatch.setattr(bench_core, "_detect_platform", lambda: "tpu")
    bench_core.report("single_client_tasks_async", 9999.0, "tasks/s")
    rec = bench_core.RESULTS[-1]
    assert rec["platform"] == "tpu"
    assert rec["vs_baseline"] is None

    monkeypatch.setattr(
        bench_core, "_detect_platform", lambda: bench_core.BASELINE_PLATFORM
    )
    bench_core.report("single_client_tasks_async", 9999.0, "tasks/s")
    rec = bench_core.RESULTS[-1]
    assert rec["platform"] == bench_core.BASELINE_PLATFORM
    assert rec["vs_baseline"] is not None
