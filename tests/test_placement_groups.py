"""Placement group tests (reference: python/ray/tests/test_placement_group.py)."""

import pytest

import ray_tpu
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)


def test_pg_create_and_ready(ray_start_regular):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(10)
    remove_placement_group(pg)


def test_pg_reserves_resources(ray_start_regular):
    pg = placement_group([{"CPU": 2}], strategy="STRICT_PACK")
    assert pg.wait(10)
    avail = ray_tpu.available_resources()
    assert avail.get("CPU", 0) == 0.0
    remove_placement_group(pg)
    avail = ray_tpu.available_resources()
    assert avail["CPU"] == 2.0


def test_task_in_pg_bundle(ray_start_regular):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}])
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def where():
        return "ok"

    r = where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    ).remote()
    assert ray_tpu.get(r, timeout=30) == "ok"
    remove_placement_group(pg)


def test_actor_in_pg(ray_start_regular):
    pg = placement_group([{"CPU": 1}])
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    class A:
        def ping(self):
            return "pong"

    a = A.options(scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"
    ray_tpu.kill(a)
    remove_placement_group(pg)


def test_pg_ready_object_ref(ray_start_regular):
    pg = placement_group([{"CPU": 1}])
    assert ray_tpu.get(pg.ready(), timeout=30) is True
    remove_placement_group(pg)


def test_pg_table(ray_start_regular):
    pg = placement_group([{"CPU": 1}], strategy="SPREAD", name="mypg")
    pg.wait(10)
    table = placement_group_table()
    assert any(v["strategy"] == "SPREAD" for v in table.values())
    remove_placement_group(pg)


def test_pg_invalid_strategy(ray_start_regular):
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="BOGUS")


def test_pg_bundle_exclusive(ray_start_regular):
    # PG reserves all CPUs; a plain task cannot run until PG removed
    pg = placement_group([{"CPU": 2}])
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def f():
        return 1

    ready, not_ready = ray_tpu.wait([f.remote()], num_returns=1, timeout=1.0)
    assert not ready  # blocked: no free CPUs outside the PG
    remove_placement_group(pg)


def test_pg_task_queues_until_ready(ray_start_regular):
    """A task in an unreserved PG must queue, not run (review finding)."""
    import time

    @ray_tpu.remote
    def blocker():
        time.sleep(6)

    # occupy both CPUs so the PG cannot reserve; poll until both blocker
    # tasks actually hold their CPUs (worker spawn can be slow under load)
    b1, b2 = blocker.remote(), blocker.remote()
    deadline = time.time() + 10
    while time.time() < deadline:
        if ray_tpu.available_resources().get("CPU", 0) == 0:
            break
        time.sleep(0.05)
    assert ray_tpu.available_resources().get("CPU", 0) == 0
    pg = placement_group([{"CPU": 2}])

    @ray_tpu.remote(num_cpus=1)
    def in_pg():
        return "ran"

    r = in_pg.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
    ).remote()
    ready, _ = ray_tpu.wait([r], num_returns=1, timeout=0.5)
    assert not ready  # must not run before the PG is reserved
    assert ray_tpu.get(r, timeout=30) == "ran"  # runs once blockers finish
    remove_placement_group(pg)


def test_pg_invalid_bundle_index_fails_task(ray_start_regular):
    """Out-of-range bundle index fails the task, not the hub (review finding)."""
    pg = placement_group([{"CPU": 1}])
    assert pg.wait(10)

    @ray_tpu.remote(num_cpus=1)
    def f():
        return 1

    r = f.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=7)
    ).remote()
    with pytest.raises(Exception):
        ray_tpu.get(r, timeout=10)
    # hub must still be alive
    assert ray_tpu.get(f.remote(), timeout=30) == 1
    remove_placement_group(pg)
