"""bench_serve.py harness smoke test (tier-1 safe, not marked slow).

Same contract as test_bench_harness.py, for the serve-plane load
generator: one --smoke micro-iteration end to end, and the --json
report must cover every BASELINES row (QPS, mixed-load percentiles,
batch efficiency, chaos success rate) — so a serve refactor that
silently breaks the closed-loop driver or the SLO registry read fails
CI instead of the next perf PR. Numbers are NOT checked.
"""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench_serve.py")


def test_smoke_run_reports_every_serve_baseline_metric(tmp_path):
    out_path = tmp_path / "bench_serve.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT)
    r = subprocess.run(
        [sys.executable, BENCH, "--smoke", "--trials", "2",
         "--json", str(out_path)],
        capture_output=True, text=True, env=env, cwd=REPO_ROOT,
        timeout=420,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"

    data = json.loads(out_path.read_text())
    assert data["mode"] == "smoke"
    assert data["trials"] == 2
    for name, rec in data["metrics"].items():
        trials = rec.get("trials")
        if trials is not None:
            assert len(trials) == 2, name
            assert (
                min(trials) - 0.01 <= rec["value"] <= max(trials) + 0.01
            ), (name, rec["value"], trials)

    sys.path.insert(0, REPO_ROOT)
    try:
        from bench_serve import BASELINES
    finally:
        sys.path.remove(REPO_ROOT)

    missing = set(BASELINES) - set(data["metrics"])
    assert not missing, f"BASELINES metrics missing from report: {missing}"
    # platform stamping (PR 20): the run-level platform plus one stamp
    # per row, same contract as bench_core — BASELINES are cpu-box
    # numbers, so off-platform rows must carry vs_baseline=None
    from bench_core import BASELINE_PLATFORM

    assert data["platform"] == BASELINE_PLATFORM  # JAX_PLATFORMS=cpu above
    for name, rec in data["metrics"].items():
        assert rec.get("platform"), f"{name} row missing platform stamp"
        if rec["platform"] != BASELINE_PLATFORM:
            assert rec["vs_baseline"] is None, name
    for name, rec in data["metrics"].items():
        assert rec["value"] > 0, f"{name} reported a non-positive value"
    # efficiency and success-rate rows are ratios in (0, 1]
    assert 0 < data["metrics"]["serve_batch_efficiency"]["value"] <= 1.0
    assert 0 < data["metrics"]["serve_chaos_success_rate"]["value"] <= 1.0
    # PR 15 resilience rows: the autoscale-under-chaos success rate is
    # a ratio with a hard 0.99 floor (asserted inside the bench — here
    # we only check the row shape survived), its p99 is a real latency,
    # and a shed reject is measured in sub-ms territory, not seconds
    auto = data["metrics"]["serve_autoscale_chaos_success_rate"]["value"]
    assert 0.99 <= auto <= 1.0
    assert data["metrics"]["serve_autoscale_chaos_p99_ms"]["value"] > 0
    assert 0 < data["metrics"]["serve_shed_reject_p50_ms"]["value"] < 1000
    # every stdout metric line is one JSON object (the scrapeable form)
    parsed = [
        json.loads(line) for line in r.stdout.splitlines()
        if line.startswith("{")
    ]
    assert {p["metric"] for p in parsed} >= set(BASELINES)
