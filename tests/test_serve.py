"""Serve tests (pattern: python/ray/serve/tests/ — deployments against
a real runtime; routing, composition, batching, autoscaling)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cleanup(ray_start_4_cpus):
    yield
    serve.shutdown()


def test_basic_deployment(serve_cleanup):
    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"echo": x}

    handle = serve.run(Echo.bind())
    assert handle.remote("hi").result() == {"echo": "hi"}


def test_function_deployment(serve_cleanup):
    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind())
    assert handle.remote(21).result() == 42


def test_init_args_and_methods(serve_cleanup):
    @serve.deployment
    class Counter:
        def __init__(self, start):
            self.n = start

        def incr(self, by):
            self.n += by
            return self.n

    handle = serve.run(Counter.bind(10))
    assert handle.incr.remote(5).result() == 15


def test_multiple_replicas_roundrobin(serve_cleanup):
    @serve.deployment(num_replicas=2)
    class WhoAmI:
        def __call__(self, _):
            import os

            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = {handle.remote(None).result() for _ in range(16)}
    assert len(pids) == 2  # both replicas served traffic


def test_composition(serve_cleanup):
    @serve.deployment
    class Adder:
        def __init__(self, amount):
            self.amount = amount

        def __call__(self, x):
            return x + self.amount

    @serve.deployment
    class Pipeline:
        def __init__(self, a, b):
            self.a = a  # DeploymentHandles
            self.b = b

        def __call__(self, x):
            y = self.a.remote(x).result()
            return self.b.remote(y).result()

    app = Pipeline.bind(Adder.bind(1), Adder.options(name="Adder2").bind(10))
    handle = serve.run(app)
    assert handle.remote(0).result() == 11


def test_batching(serve_cleanup):
    @serve.deployment(max_ongoing_requests=32)
    class Batched:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def __call__(self, items):
            # whole batch processed at once
            n = len(items)
            return [{"value": x * 2, "batch_size": n} for x in items]

    handle = serve.run(Batched.bind())
    responses = [handle.remote(i) for i in range(8)]
    results = [r.result() for r in responses]
    assert [r["value"] for r in results] == [i * 2 for i in range(8)]
    assert max(r["batch_size"] for r in results) > 1  # actually batched


def test_redeploy_new_version(serve_cleanup):
    @serve.deployment
    class V:
        def __call__(self, _):
            return 1

    serve.run(V.bind())

    @serve.deployment(name="V")
    class V2:
        def __call__(self, _):
            return 2

    handle = serve.run(V2.bind())
    deadline = time.time() + 20
    while time.time() < deadline:
        if handle.remote(None).result() == 2:
            break
        time.sleep(0.2)
    assert handle.remote(None).result() == 2


def test_status_and_delete(serve_cleanup):
    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, _):
            return "ok"

    serve.run(S.bind())
    st = serve.status()
    assert "S" in st["applications"]
    serve.delete("S")
    deadline = time.time() + 10
    while time.time() < deadline and "S" in serve.status()["applications"]:
        time.sleep(0.1)
    assert "S" not in serve.status()["applications"]


def test_http_ingress(serve_cleanup):
    @serve.deployment
    class App:
        def __call__(self, request):
            return {"path": request["path"], "method": request["method"]}

    serve.run(App.bind(), route_prefix="/api", http_options={"port": 18765})
    import json
    import urllib.request

    deadline = time.time() + 15
    last = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen("http://127.0.0.1:18765/api/x", timeout=5) as r:
                last = json.loads(r.read())
            break
        except Exception as e:
            last = e
            time.sleep(0.3)
    assert isinstance(last, dict), last
    assert last == {"path": "/api/x", "method": "GET"}


def test_local_testing_mode_no_cluster():
    """serve.run(app, local_testing_mode=True) runs the whole app
    in-process: no controller, no actors, composition + multiplexing +
    streaming still behave (reference: serve local_testing_mode)."""
    from ray_tpu import serve

    @serve.deployment
    class Embedder:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Head:
        def __init__(self, embedder):
            self.embedder = embedder

        def __call__(self, x):
            return self.embedder.remote(x).result() + 1

        async def agen(self, n):
            return [i for i in range(n)]

        def stream(self, n):
            for i in range(n):
                yield i * 10

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, mid):
            return mid.upper()

        def which_model(self):
            return self.get_model(serve.get_multiplexed_model_id())

    h = serve.run(Head.bind(Embedder.bind()), local_testing_mode=True)
    assert h.remote(10).result() == 21
    assert h.agen.remote(3).result() == [0, 1, 2]
    got = list(h.options(stream=True).stream.remote(3))
    assert got == [0, 10, 20]
    assert h.options(multiplexed_model_id="ma").which_model.remote().result() == "MA"
    # errors surface at .result(), not submission
    @serve.deployment
    def boom():
        raise ValueError("nope")

    bh = serve.run(boom.bind(), local_testing_mode=True)
    resp = bh.remote()
    import pytest as _pytest

    with _pytest.raises(ValueError, match="nope"):
        resp.result()


def test_local_testing_mode_async_callers():
    """Local handles work from async code: `await resp` resolves lazy
    coroutines; async generators stream natively."""
    import asyncio

    from ray_tpu import serve

    @serve.deployment
    class A:
        async def compute(self, x):
            await asyncio.sleep(0)
            return x + 1

        async def astream(self, n):
            for i in range(n):
                yield i * 2

    h = serve.run(A.bind(), local_testing_mode=True)

    async def drive():
        v = await h.compute.remote(4)
        items = []
        async for item in h.options(stream=True).astream.remote(3):
            items.append(item)
        return v, items

    v, items = asyncio.run(drive())
    assert v == 5
    assert items == [0, 2, 4]
    # sync caller can also drain an async generator
    assert list(h.options(stream=True).astream.remote(2)) == [0, 2]


# --------------------------------------------------------------- gRPC
def test_grpc_ingress(ray_start_4_cpus):
    """gRPC ingress (reference: serve/_private/proxy.py gRPCProxy):
    unary calls route by metadata/route-prefix to deployments over a
    generic raw-bytes service — real HTTP/2 gRPC, no protoc step."""
    import json as _json

    import grpc

    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, req):
            assert req["grpc_method"].endswith("/Predict")
            body = req["body"]
            return {"upper": body.decode().upper(),
                    "via": req["metadata"].get("route", "")}

    serve.start(grpc_options={"port": 0})
    port = serve.grpc_port()
    serve.run(Echo.bind(), route_prefix="/echo")
    try:
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        call = chan.unary_unary(
            "/any.Service/Predict",
            request_serializer=None,
            response_deserializer=None,
        )
        out = call(b"hello", metadata=(("route", "/echo"),), timeout=30)
        parsed = _json.loads(out)
        assert parsed == {"upper": "HELLO", "via": "/echo"}

        # unknown route -> NOT_FOUND status
        with pytest.raises(grpc.RpcError) as ei:
            call(b"x", metadata=(("route", "/nope"),), timeout=30)
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND
        chan.close()
    finally:
        serve.shutdown()


def test_http_content_type_negotiation(serve_cleanup):
    """Non-JSON payloads (reference: starlette Response passthrough):
    bytes get octet-stream, serve.Response controls status/content-type
    /headers explicitly — no silent JSON coercion of binary bodies."""
    import urllib.request

    @serve.deployment
    class Bin:
        def __call__(self, req):
            if req["path"].endswith("/png"):
                return serve.Response(
                    b"\x89PNG...", content_type="image/png",
                    headers={"X-Model": "demo"},
                )
            if req["path"].endswith("/teapot"):
                return serve.Response("short and stout", status=418)
            if req["path"].endswith("/hdr"):
                # starlette-style: type via headers, charset in value
                return serve.Response(
                    "<b>hi</b>",
                    headers={"Content-Type": "text/html; charset=utf-8"},
                )
            return bytes(range(16))

    serve.run(Bin.bind(), route_prefix="/bin",
              http_options={"port": 18767})
    base = "http://127.0.0.1:18767/bin"
    deadline = time.time() + 15
    r = None
    while time.time() < deadline:
        try:
            r = urllib.request.urlopen(base + "/raw", timeout=5)
            break
        except Exception:
            time.sleep(0.3)
    assert r is not None
    assert r.headers["Content-Type"] == "application/octet-stream"
    assert r.read() == bytes(range(16))

    r = urllib.request.urlopen(base + "/png", timeout=10)
    assert r.headers["Content-Type"] == "image/png"
    assert r.headers["X-Model"] == "demo"
    assert r.read().startswith(b"\x89PNG")

    r = urllib.request.urlopen(base + "/hdr", timeout=10)
    assert r.headers["Content-Type"].startswith("text/html")
    assert r.read() == b"<b>hi</b>"

    import urllib.error
    try:
        urllib.request.urlopen(base + "/teapot", timeout=10)
        assert False, "expected 418"
    except urllib.error.HTTPError as e:
        assert e.code == 418
        assert e.read() == b"short and stout"
