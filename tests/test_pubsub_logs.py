"""Pubsub channels + worker log streaming to the driver (reference:
src/ray/pubsub/ + log_monitor.py driver log forwarding)."""

import time

import pytest

import ray_tpu


def test_pubsub_roundtrip(ray_start_regular):
    client = ray_tpu._private.worker.get_client()
    got = []
    client.subscribe("my_channel", got.append)

    @ray_tpu.remote
    def publisher():
        c = ray_tpu._private.worker.get_client()
        for i in range(3):
            c.publish("my_channel", {"i": i})
        c.flush()
        return True

    assert ray_tpu.get(publisher.remote(), timeout=30)
    deadline = time.time() + 10
    while time.time() < deadline and len(got) < 3:
        time.sleep(0.05)
    assert [m["i"] for m in got] == [0, 1, 2]


def test_worker_prints_reach_driver(ray_start_regular, capsys):
    @ray_tpu.remote
    def chatty():
        print("hello from the worker side")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=30) == 1
    deadline = time.time() + 10
    while time.time() < deadline:
        out = capsys.readouterr().out
        if "hello from the worker side" in out:
            assert "(worker pid=" in out
            return
        time.sleep(0.1)
    pytest.fail("worker stdout never reached the driver")
