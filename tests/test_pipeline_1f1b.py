"""1F1B pipeline training: gradient correctness vs a single-device
reference, equivalence with GPipe, and the 1F1B memory win (smaller
activation stash => smaller compiled temp memory)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.parallel.pipeline import pipeline_train

P_STAGES = 4
FDIM = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


@pytest.fixture(scope="module")
def setup():
    devs = jax.devices()[:P_STAGES]
    mesh = Mesh(np.asarray(devs), ("pipe",))
    k = jax.random.PRNGKey(0)
    stacked = {
        "w": jax.random.normal(k, (P_STAGES, FDIM, FDIM)) * 0.3,
        "b": jnp.zeros((P_STAGES, FDIM)),
    }
    batch = jax.random.normal(jax.random.PRNGKey(1), (32, FDIM))
    targets = jax.random.normal(jax.random.PRNGKey(2), (32, FDIM))
    return mesh, stacked, batch, targets


def _reference(stacked, batch, targets, microbatch=4):
    """Single-device truth: same microbatched loss/grad computation."""

    def full_loss(params):
        M = batch.shape[0] // microbatch
        total = 0.0
        for m in range(M):
            x = batch[m * microbatch:(m + 1) * microbatch]
            t = targets[m * microbatch:(m + 1) * microbatch]
            for p in range(P_STAGES):
                x = _stage_fn(jax.tree.map(lambda v: v[p], params), x)
            total = total + _loss_fn(x, t)
        return total / M

    loss, grads = jax.value_and_grad(full_loss)(stacked)
    return loss, grads


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
def test_grads_match_single_device(setup, schedule):
    mesh, stacked, batch, targets = setup
    run = pipeline_train(
        _stage_fn, stacked, mesh, loss_fn=_loss_fn,
        microbatch_size=4, schedule=schedule,
    )
    loss, grads = jax.jit(run)(batch, targets)
    ref_loss, ref_grads = _reference(stacked, batch, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for key in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[key]), np.asarray(ref_grads[key]),
            atol=1e-5, rtol=1e-5, err_msg=f"{schedule}:{key}",
        )


def test_1f1b_uses_less_memory_than_gpipe(setup):
    """The point of 1F1B: stash bounded by 2P-1 instead of M. Assert via
    XLA's compiled memory analysis (temp allocation covers the stash)."""
    mesh, stacked, _, _ = setup
    big_batch = jax.random.normal(jax.random.PRNGKey(3), (128, FDIM))
    big_targets = jax.random.normal(jax.random.PRNGKey(4), (128, FDIM))

    sizes = {}
    for schedule in ("1f1b", "gpipe"):
        run = pipeline_train(
            _stage_fn, stacked, mesh, loss_fn=_loss_fn,
            microbatch_size=4, schedule=schedule,  # M=32 microbatches
        )
        compiled = jax.jit(run).lower(big_batch, big_targets).compile()
        sizes[schedule] = compiled.memory_analysis().temp_size_in_bytes

    assert sizes["1f1b"] < sizes["gpipe"], sizes
    # loose sanity on the ratio: stash 2P-1=7 vs M=32 slots
    assert sizes["1f1b"] < 0.7 * sizes["gpipe"], sizes
