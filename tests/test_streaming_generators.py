"""Streaming generators (`num_returns="streaming"`) — reference parity:
_raylet.pyx:280 ObjectRefGenerator. Incremental refs from task and actor
generators, error-as-final-ref semantics, backpressure, async actors."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import ObjectRefGenerator
from ray_tpu.exceptions import TaskError


def test_task_generator_streams(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, ObjectRefGenerator)
    vals = [ray_tpu.get(ref) for ref in g]
    assert vals == [0, 10, 20, 30, 40]


def test_incremental_delivery(ray_start_regular):
    """First value is consumable before the generator finishes."""
    import time

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        time.sleep(2.0)
        yield "second"

    g = slow_gen.remote()
    t0 = time.time()
    first_ref = next(g)
    assert ray_tpu.get(first_ref) == "first"
    assert time.time() - t0 < 1.5  # did not wait for the full generator
    assert ray_tpu.get(next(g)) == "second"


def test_generator_error_surfaces_as_final_ref(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        raise ValueError("boom")

    g = bad.remote()
    assert ray_tpu.get(next(g)) == 1
    with pytest.raises(TaskError):
        ray_tpu.get(next(g))
    with pytest.raises(StopIteration):
        next(g)


def test_large_values_stream_through_shm(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def chunks():
        for i in range(3):
            yield np.full((300_000,), float(i))

    for i, ref in enumerate(chunks.remote()):
        arr = ray_tpu.get(ref)
        assert arr[0] == float(i) and arr.shape == (300_000,)


def test_backpressure_bounds_producer(ray_start_regular):
    @ray_tpu.remote(
        num_returns="streaming", _generator_backpressure_num_objects=2
    )
    def gen():
        import os, time

        for i in range(6):
            yield i

    g = gen.remote()
    # consume slowly; producer must not run unboundedly ahead (it blocks
    # on credit after 2 unconsumed). Just verify full delivery/order.
    out = [ray_tpu.get(r) for r in g]
    assert out == list(range(6))


def test_actor_sync_generator(ray_start_regular):
    @ray_tpu.remote
    class Gen:
        def stream(self, n):
            for i in range(n):
                yield f"item{i}"

    a = Gen.remote()
    vals = [
        ray_tpu.get(r)
        for r in a.stream.options(num_returns="streaming").remote(3)
    ]
    assert vals == ["item0", "item1", "item2"]


def test_actor_async_generator(ray_start_regular):
    @ray_tpu.remote
    class AGen:
        async def stream(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * i

    a = AGen.remote()
    vals = [
        ray_tpu.get(r)
        for r in a.stream.options(num_returns="streaming").remote(4)
    ]
    assert vals == [0, 1, 4, 9]


def test_failure_before_first_yield_ends_stream(ray_start_regular):
    """Arg-binding/decode errors happen before the generator exists; the
    stream must still end with the error (review finding: consumer hung
    forever otherwise)."""

    @ray_tpu.remote(num_returns="streaming")
    def gen(a, b):
        yield a + b

    g = gen.remote(1)  # TypeError: missing positional arg
    with pytest.raises(TaskError):
        ray_tpu.get(next(g), timeout=15)
    with pytest.raises(StopIteration):
        next(g)


def test_worker_death_ends_stream_with_error(ray_start_regular):
    @ray_tpu.remote(num_returns="streaming")
    def dies():
        import os

        yield 1
        os._exit(1)

    g = dies.remote()
    assert ray_tpu.get(next(g)) == 1
    with pytest.raises(Exception):
        ray_tpu.get(next(g), timeout=10)
