"""DAG + channel tests (pattern: python/ray/dag/tests/ +
experimental/channel tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import ShmChannel


def test_function_dag(ray_start_4_cpus):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def square(x):
        return x * x

    with InputNode() as inp:
        dag = square.bind(add.bind(inp, 3))
    ref = dag.execute(2)
    assert ray_tpu.get(ref) == 25


def test_actor_dag_state(ray_start_4_cpus):
    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    acc = Acc.remote()
    with InputNode() as inp:
        dag = acc.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 5
    assert ray_tpu.get(dag.execute(7)) == 12  # state persists


def test_multi_output(ray_start_4_cpus):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def dec(x):
        return x - 1

    with InputNode() as inp:
        dag = MultiOutputNode([inc.bind(inp), dec.bind(inp)])
    refs = dag.execute(10)
    assert ray_tpu.get(refs) == [11, 9]


def test_input_attribute(ray_start_4_cpus):
    @ray_tpu.remote
    def mul(a, b):
        return a * b

    with InputNode() as inp:
        dag = mul.bind(inp["x"], inp["y"])
    assert ray_tpu.get(dag.execute({"x": 3, "y": 4})) == 12


def test_compiled_dag_pipelining(ray_start_4_cpus):
    @ray_tpu.remote
    class Stage:
        def __init__(self, mult):
            self.mult = mult

        def run(self, x):
            return x * self.mult

    s1, s2 = Stage.remote(2), Stage.remote(10)
    with InputNode() as inp:
        dag = s2.run.bind(s1.run.bind(inp))
    compiled = dag.experimental_compile(max_inflight_executions=4)
    refs = [compiled.execute(i) for i in range(8)]  # overlapped
    assert [r.get() for r in refs] == [i * 20 for i in range(8)]
    compiled.teardown()


def test_shm_channel_roundtrip(ray_start_4_cpus):
    ch = ShmChannel.create(shape=(4,), dtype="float32", capacity=2)
    try:
        @ray_tpu.remote
        def producer(ch, n):
            for i in range(n):
                ch.write(np.full((4,), float(i), dtype=np.float32))
            return True

        ref = producer.remote(ch, 5)
        got = [ch.read() for _ in range(5)]
        assert ray_tpu.get(ref) is True
        for i, arr in enumerate(got):
            np.testing.assert_allclose(arr, np.full((4,), float(i)))
    finally:
        ch.close(unlink=True)


def test_shm_channel_shape_check():
    ch = ShmChannel.create(shape=(2, 2), dtype="float32")
    try:
        with pytest.raises(ValueError, match="shape"):
            ch.write(np.zeros((3,), dtype=np.float32))
    finally:
        ch.close(unlink=True)
