"""DAG + channel tests (pattern: python/ray/dag/tests/ +
experimental/channel tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import ShmChannel


def test_function_dag(ray_start_4_cpus):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def square(x):
        return x * x

    with InputNode() as inp:
        dag = square.bind(add.bind(inp, 3))
    ref = dag.execute(2)
    assert ray_tpu.get(ref) == 25


def test_actor_dag_state(ray_start_4_cpus):
    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    acc = Acc.remote()
    with InputNode() as inp:
        dag = acc.add.bind(inp)
    assert ray_tpu.get(dag.execute(5)) == 5
    assert ray_tpu.get(dag.execute(7)) == 12  # state persists


def test_multi_output(ray_start_4_cpus):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def dec(x):
        return x - 1

    with InputNode() as inp:
        dag = MultiOutputNode([inc.bind(inp), dec.bind(inp)])
    refs = dag.execute(10)
    assert ray_tpu.get(refs) == [11, 9]


def test_input_attribute(ray_start_4_cpus):
    @ray_tpu.remote
    def mul(a, b):
        return a * b

    with InputNode() as inp:
        dag = mul.bind(inp["x"], inp["y"])
    assert ray_tpu.get(dag.execute({"x": 3, "y": 4})) == 12


def test_compiled_dag_pipelining(ray_start_4_cpus):
    @ray_tpu.remote
    class Stage:
        def __init__(self, mult):
            self.mult = mult

        def run(self, x):
            return x * self.mult

    s1, s2 = Stage.remote(2), Stage.remote(10)
    with InputNode() as inp:
        dag = s2.run.bind(s1.run.bind(inp))
    compiled = dag.experimental_compile(max_inflight_executions=4)
    refs = [compiled.execute(i) for i in range(8)]  # overlapped
    assert [r.get() for r in refs] == [i * 20 for i in range(8)]
    compiled.teardown()


def test_shm_channel_roundtrip(ray_start_4_cpus):
    ch = ShmChannel.create(shape=(4,), dtype="float32", capacity=2)
    try:
        @ray_tpu.remote
        def producer(ch, n):
            for i in range(n):
                ch.write(np.full((4,), float(i), dtype=np.float32))
            return True

        ref = producer.remote(ch, 5)
        got = [ch.read() for _ in range(5)]
        assert ray_tpu.get(ref) is True
        for i, arr in enumerate(got):
            np.testing.assert_allclose(arr, np.full((4,), float(i)))
    finally:
        ch.close(unlink=True)


def test_shm_channel_shape_check():
    ch = ShmChannel.create(shape=(2, 2), dtype="float32")
    try:
        with pytest.raises(ValueError, match="shape"):
            ch.write(np.zeros((3,), dtype=np.float32))
    finally:
        ch.close(unlink=True)


# ----------------------------------------------------- collective nodes
def test_compiled_dag_allreduce_zero_roundtrips(ray_start_4_cpus):
    """In-DAG allreduce (reference: dag/collective_node.py over the
    Communicator ABC): two actors each transform the input, the
    compiled loops exchange + reduce over the pre-allocated channel
    mesh, and the driver reads identical reduced results from both —
    with ZERO scheduler tasks per tick."""
    import numpy as np

    from ray_tpu.dag import InputNode, MultiOutputNode, allreduce

    @ray_tpu.remote
    class Scale:
        def __init__(self, k):
            self.k = k

        def run(self, x):
            return x * self.k

    a, b = Scale.remote(2.0), Scale.remote(3.0)
    with InputNode() as inp:
        na = a.run.bind(inp).with_shm_channel((4,))
        nb = b.run.bind(inp).with_shm_channel((4,))
        ra, rb = allreduce.bind([na, nb], op="sum")
        dag = MultiOutputNode([ra, rb])
    compiled = dag.experimental_compile(max_inflight_executions=4)
    assert compiled._channel_mode

    # warm tick
    out = compiled.execute(np.ones(4, np.float32)).get(timeout=30)
    np.testing.assert_allclose(out[0], np.full(4, 5.0))
    np.testing.assert_allclose(out[1], np.full(4, 5.0))

    def n_tasks():
        return len(ray_tpu._private.worker.get_client().list_state("tasks"))

    before = n_tasks()
    refs = [compiled.execute(np.full(4, float(i), np.float32)) for i in range(6)]
    outs = [r.get(timeout=30) for r in refs]
    assert n_tasks() == before, "allreduce ticks must not submit tasks"
    for i, (x, y) in enumerate(outs):
        np.testing.assert_allclose(x, np.full(4, 5.0 * i))
        np.testing.assert_allclose(y, x)  # bit-identical across ranks
    compiled.teardown()


def test_compiled_dag_allreduce_ops_and_legacy(ray_start_4_cpus):
    import numpy as np

    from ray_tpu.dag import InputNode, MultiOutputNode, allreduce

    @ray_tpu.remote
    class Add:
        def __init__(self, k):
            self.k = k

        def run(self, x):
            return x + self.k

    a, b = Add.remote(1.0), Add.remote(10.0)
    with InputNode() as inp:
        na = a.run.bind(inp).with_shm_channel((2,))
        nb = b.run.bind(inp).with_shm_channel((2,))
        ra, rb = allreduce.bind([na, nb], op="max")
        dag = MultiOutputNode([ra, rb])
    compiled = dag.experimental_compile()
    out = compiled.execute(np.zeros(2, np.float32)).get(timeout=30)
    np.testing.assert_allclose(out[0], np.full(2, 10.0))
    compiled.teardown()

    # legacy (non-channel) mode reduces driver-side with identical
    # semantics
    with InputNode() as inp:
        na = a.run.bind(inp)
        nb = b.run.bind(inp)
        ra, rb = allreduce.bind([na, nb], op="sum")
        dag = MultiOutputNode([ra, rb])
    compiled = dag.experimental_compile()
    assert not compiled._channel_mode
    ref = compiled.execute(np.zeros(2, np.float32))
    vals = ref.get(timeout=30)
    np.testing.assert_allclose(vals[0], np.full(2, 11.0))
    np.testing.assert_allclose(vals[1], vals[0])


def test_allreduce_bind_validation(ray_start_4_cpus):
    import pytest as _pytest

    from ray_tpu.dag import InputNode, allreduce

    @ray_tpu.remote
    class A:
        def run(self, x):
            return x

    a = A.remote()
    with InputNode() as inp:
        n1 = a.run.bind(inp)
        n2 = a.run.bind(inp)
        with _pytest.raises(ValueError, match="distinct actors"):
            allreduce.bind([n1, n2])
        with _pytest.raises(ValueError, match="at least two"):
            allreduce.bind([n1])
        with _pytest.raises(ValueError, match="unsupported allreduce op"):
            allreduce.bind([n1, n2], op="xor")
