"""Data library tests.

Pattern from the reference (python/ray/data/tests/): small datasets
against a real runtime; assert transform semantics, shuffle/sort
correctness, actor-pool UDFs, iteration formats.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data import ActorPoolStrategy, Count, Max, Mean, Sum


@pytest.fixture
def ray4(ray_start_4_cpus):
    yield ray_start_4_cpus


class TestBasics:
    def test_range_count_take(self, ray4):
        ds = rd.range(100)
        assert ds.count() == 100
        rows = ds.take(5)
        assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]

    def test_from_items(self, ray4):
        ds = rd.from_items([{"x": i, "y": i * 2} for i in range(10)])
        assert ds.count() == 10
        assert ds.take(2) == [{"x": 0, "y": 0}, {"x": 1, "y": 2}]

    def test_schema_columns(self, ray4):
        ds = rd.range(10)
        assert ds.schema() == {"id": "int64"}
        assert ds.columns() == ["id"]

    def test_from_numpy_pandas(self, ray4):
        import pandas as pd

        ds = rd.from_numpy(np.arange(12).reshape(4, 3))
        assert ds.count() == 4
        df = rd.from_pandas(pd.DataFrame({"a": [1, 2], "b": [3.0, 4.0]})).to_pandas()
        assert list(df["a"]) == [1, 2]


class TestTransforms:
    def test_map(self, ray4):
        ds = rd.range(10).map(lambda r: {"id": r["id"] * 2})
        assert [r["id"] for r in ds.take(3)] == [0, 2, 4]

    def test_filter(self, ray4):
        ds = rd.range(20).filter(lambda r: r["id"] % 2 == 0)
        assert ds.count() == 10

    def test_flat_map(self, ray4):
        ds = rd.from_items([{"x": 1}, {"x": 2}]).flat_map(
            lambda r: [{"x": r["x"]}, {"x": -r["x"]}]
        )
        assert sorted(r["x"] for r in ds.take_all()) == [-2, -1, 1, 2]

    def test_map_batches_numpy(self, ray4):
        ds = rd.range(32).map_batches(lambda b: {"id": b["id"] + 1})
        assert [r["id"] for r in ds.take(3)] == [1, 2, 3]

    def test_map_batches_batch_size(self, ray4):
        sizes = []

        def record(b):
            return {"n": np.array([len(b["id"])])}

        ds = rd.range(100, override_num_blocks=1).map_batches(record, batch_size=30)
        got = sorted(r["n"] for r in ds.take_all())
        assert got == [10, 30, 30, 30]

    def test_map_batches_pandas_format(self, ray4):
        def f(df):
            df["y"] = df["id"] * 3
            return df

        ds = rd.range(10).map_batches(f, batch_format="pandas")
        assert ds.take(2)[1]["y"] == 3

    def test_fusion_chains_maps(self, ray4):
        from ray_tpu.data._internal.executor import build_stages

        ds = rd.range(10).map(lambda r: r).filter(lambda r: True).map_batches(lambda b: b)
        stages = build_stages(ds._logical)
        # read + 3 one-to-one ops fuse into ONE read stage
        assert len(stages) == 1
        assert stages[0].kind == "read"

    def test_add_drop_select_columns(self, ray4):
        ds = rd.range(5).add_column("sq", lambda b: b["id"] ** 2)
        assert ds.take(3)[2]["sq"] == 4
        assert ds.drop_columns(["sq"]).columns() == ["id"]
        assert ds.select_columns(["sq"]).columns() == ["sq"]

    def test_limit(self, ray4):
        assert rd.range(100).limit(7).count() == 7


class TestActorPool:
    def test_class_udf_actor_pool(self, ray4):
        class AddConst:
            def __init__(self, c):
                self.c = c

            def __call__(self, batch):
                return {"id": batch["id"] + self.c}

        ds = rd.range(16).map_batches(
            AddConst,
            fn_constructor_args=(100,),
            compute=ActorPoolStrategy(size=2),
        )
        vals = sorted(r["id"] for r in ds.take_all())
        assert vals == list(range(100, 116))


class TestShufflesSorts:
    def test_repartition(self, ray4):
        ds = rd.range(20).repartition(4).materialize()
        assert ds.num_blocks() == 4
        assert ds.count() == 20

    def test_random_shuffle_preserves_rows(self, ray4):
        ds = rd.range(50).random_shuffle(seed=42)
        vals = sorted(r["id"] for r in ds.take_all())
        assert vals == list(range(50))

    def test_sort(self, ray4):
        ds = rd.from_items([{"v": x} for x in [5, 3, 9, 1, 7, 2, 8]]).sort("v")
        assert [r["v"] for r in ds.take_all()] == [1, 2, 3, 5, 7, 8, 9]

    def test_sort_descending(self, ray4):
        ds = rd.from_items([{"v": x} for x in [5, 3, 9]]).sort("v", descending=True)
        assert [r["v"] for r in ds.take_all()] == [9, 5, 3]

    def test_groupby_aggregate(self, ray4):
        items = [{"k": i % 3, "v": float(i)} for i in range(12)]
        ds = rd.from_items(items).groupby("k").sum("v")
        rows = sorted(ds.take_all(), key=lambda r: r["k"])
        assert [r["sum(v)"] for r in rows] == [18.0, 22.0, 26.0]

    def test_global_aggregate(self, ray4):
        out = rd.range(10).aggregate(Sum("id"), Max("id"), Mean("id"))
        assert out["sum(id)"] == 45
        assert out["max(id)"] == 9
        assert out["mean(id)"] == 4.5

    def test_union_zip(self, ray4):
        a = rd.from_items([{"x": 1}, {"x": 2}])
        b = rd.from_items([{"x": 3}])
        assert a.union(b).count() == 3
        z = rd.from_items([{"l": 1}]).zip(rd.from_items([{"r": 2}]))
        assert z.take_all() == [{"l": 1, "r": 2}]


class TestConsumption:
    def test_iter_batches_sizes(self, ray4):
        batches = list(rd.range(25).iter_batches(batch_size=10))
        assert [len(b["id"]) for b in batches] == [10, 10, 5]

    def test_iter_batches_drop_last(self, ray4):
        batches = list(rd.range(25).iter_batches(batch_size=10, drop_last=True))
        assert [len(b["id"]) for b in batches] == [10, 10]

    def test_iter_batches_device_put(self, ray4):
        import jax

        dev = jax.devices()[0]
        batches = list(
            rd.range(8).iter_batches(batch_size=8, device_put=dev)
        )
        assert len(batches) == 1
        assert isinstance(batches[0]["id"], jax.Array)

    def test_split(self, ray4):
        parts = rd.range(10).split(2)
        assert [p.count() for p in parts] == [5, 5]

    def test_take_batch(self, ray4):
        b = rd.range(100).take_batch(5)
        np.testing.assert_array_equal(b["id"], np.arange(5))

    def test_streaming_split_coordinated(self, ray4):
        """One execution feeds N pull-based consumers: uneven consumers
        drain the dataset exactly once, the fast consumer claims more,
        and the next epoch re-executes fully (reference:
        Dataset.streaming_split coordination)."""
        import threading
        import time

        splits = rd.range(80).map_batches(
            lambda b: b, batch_size=8
        ).streaming_split(2)
        got = {0: [], 1: []}

        def consume(i, delay):
            for row in splits[i].iter_rows():
                got[i].append(row["id"])
                time.sleep(delay)

        ts = [
            threading.Thread(target=consume, args=(0, 0.0)),
            threading.Thread(target=consume, args=(1, 0.02)),
        ]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert sorted(got[0] + got[1]) == list(range(80))  # exactly once
        assert len(got[0]) > len(got[1])  # demand-balanced
        # epoch 2: the plan re-executes and drains fully again
        epoch2 = []

        def consume2(i):
            for row in splits[i].iter_rows():
                epoch2.append(row["id"])

        ts = [
            threading.Thread(target=consume2, args=(i,)) for i in (0, 1)
        ]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert sorted(epoch2) == list(range(80))

    def test_iter_torch_batches(self, ray4):
        import torch

        b = next(iter(rd.range(6).iter_torch_batches(batch_size=6)))
        assert isinstance(b["id"], torch.Tensor)


class TestIO:
    def test_parquet_roundtrip(self, ray4, tmp_path):
        path = str(tmp_path / "pq")
        rd.range(30).write_parquet(path)
        ds = rd.read_parquet(path)
        assert ds.count() == 30
        assert sorted(r["id"] for r in ds.take_all()) == list(range(30))

    def test_csv_roundtrip(self, ray4, tmp_path):
        path = str(tmp_path / "csv")
        rd.from_items([{"a": i, "b": i * 1.5} for i in range(10)]).write_csv(path)
        ds = rd.read_csv(path)
        assert ds.count() == 10

    def test_json_roundtrip(self, ray4, tmp_path):
        path = str(tmp_path / "js")
        rd.from_items([{"a": i} for i in range(5)]).write_json(path)
        assert rd.read_json(path).count() == 5

    def test_read_text(self, ray4, tmp_path):
        p = tmp_path / "t.txt"
        p.write_text("alpha\nbeta\ngamma\n")
        ds = rd.read_text(str(p))
        assert [r["text"] for r in ds.take_all()] == ["alpha", "beta", "gamma"]

    def test_read_binary(self, ray4, tmp_path):
        p = tmp_path / "b.bin"
        p.write_bytes(b"\x00\x01")
        rows = rd.read_binary_files(str(p)).take_all()
        assert rows[0]["bytes"] == b"\x00\x01"


class TestPushBasedShuffle:
    """Pipelined map/merge-round exchange (reference:
    push_based_shuffle_task_scheduler.py; DataContext.use_push_based_shuffle)."""

    def _rows(self, ds):
        return sorted(int(r["id"]) for r in ds.iter_rows())

    def test_push_and_pull_paths_agree(self, ray_start_regular):
        import ray_tpu.data as rd
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        orig = ctx.use_push_based_shuffle
        try:
            n = 200
            expected = list(range(n))
            for flag in (True, False):
                ctx.use_push_based_shuffle = flag
                ds = rd.range(n, override_num_blocks=9).random_shuffle(seed=4)
                assert self._rows(ds) == expected, f"push={flag}"
                ds = rd.range(n, override_num_blocks=9).repartition(3)
                assert self._rows(ds) == expected, f"push={flag}"
                ds = rd.range(n, override_num_blocks=9).sort("id")
                got = [int(r["id"]) for r in ds.iter_rows()]
                assert got == expected, f"push={flag}"
        finally:
            ctx.use_push_based_shuffle = orig

    def test_partial_merge_rounds_bound_fan_in(self, ray_start_regular):
        """With M maps, each partition's final merge consumes
        O(sqrt(M)) partial refs, not M."""
        from ray_tpu.data._internal.executor import StreamingExecutor
        import ray_tpu

        ex = StreamingExecutor([])
        refs = [ray_tpu.put({"id": __import__("numpy").arange(4) + 4 * i}) for i in range(16)]
        k = 4
        calls = []

        def submit(ref):
            calls.append(ref)
            split = ray_tpu.remote(
                lambda b, kk=k: [
                    {key: v[i::kk] for key, v in b.items()} for i in range(kk)
                ]
            ).options(num_returns=k)
            return split.remote(ref)

        parts = ex._exchange_parts(refs, submit, k)
        assert len(calls) == 16
        # 16 maps -> rounds of 4 -> 4 partials per partition
        assert all(len(p) == 4 for p in parts)


def test_read_images(ray_start_regular, tmp_path):
    """read_images: decode + resize/convert on read (reference:
    image_datasource.py)."""
    import numpy as np
    from PIL import Image

    import ray_tpu.data as rd

    for i in range(3):
        arr = np.full((10 + i, 12, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img{i}.png")

    ds = rd.read_images(str(tmp_path), size=(8, 8), mode="RGB", include_paths=True)
    rows = list(ds.iter_rows())
    assert len(rows) == 3
    for r in rows:
        assert r["image"].shape == (8, 8, 3)
        assert r["image"].dtype == np.uint8
        assert r["path"].endswith(".png")


def test_scalar_aggregates_unique_show(ray_start_regular, capsys):
    ds = rd.from_items([{"v": float(x)} for x in [3, 1, 4, 1, 5]])
    assert ds.sum("v") == 14.0
    assert ds.min("v") == 1.0
    assert ds.max("v") == 5.0
    assert abs(ds.mean("v") - 2.8) < 1e-9
    assert ds.unique("v") == [1.0, 3.0, 4.0, 5.0]
    ds.show(limit=2)
    out = capsys.readouterr().out
    assert "3.0" in out and out.count("\n") == 2


def test_scalar_aggregates_empty_dataset(ray_start_regular):
    ds = rd.from_items([])
    assert ds.sum("v") is None and ds.mean("v") is None


def test_read_webdataset(ray_start_regular, tmp_path):
    """Tar-shard samples grouped by key, typed columns decoded
    (reference: webdataset_datasource.py)."""
    import io
    import json
    import tarfile

    shard = tmp_path / "shard-000.tar"
    with tarfile.open(shard, "w") as tf:
        for i in range(3):
            for ext, payload in (
                ("img", bytes([i] * 4)),
                ("cls", str(i * 10).encode()),
                ("json", json.dumps({"i": i}).encode()),
            ):
                data = payload
                info = tarfile.TarInfo(f"sample{i}.{ext}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))

    ds = rd.read_webdataset(str(shard))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert len(rows) == 3
    assert rows[1]["cls"] == 10
    assert rows[2]["json"] == {"i": 2}
    assert rows[0]["img"] == bytes([0, 0, 0, 0])


# ------------------------------------------------------------- tfrecords
def test_tfrecords_roundtrip(ray_start_4_cpus, tmp_path):
    """Native TFRecord framing + tf.train.Example codec (reference:
    data/_internal/datasource/tfrecords_datasource.py): write shards,
    read them back with CRC verification, one column per feature."""
    import ray_tpu.data as rdata

    rows = [
        {"name": b"alpha", "score": 1.5, "count": 3, "tags": [1, 2, 3]},
        {"name": b"beta", "score": -2.25, "count": -7, "tags": [9]},
    ]
    ds = rdata.from_items(rows)
    out = str(tmp_path / "tfr")
    ds.write_tfrecords(out)

    back = rdata.read_tfrecords(out, verify_crc=True).take_all()
    back = sorted(back, key=lambda r: r["name"])
    assert back[0]["name"] == b"alpha"
    assert back[0]["score"] == pytest.approx(1.5)
    assert back[0]["count"] == 3
    assert back[0]["tags"] == [1, 2, 3]
    assert back[1]["count"] == -7  # signed int64 round trip
    assert back[1]["tags"] == 9   # singleton unwraps like the reference

    # raw mode yields framed payload bytes
    raw = rdata.read_tfrecords(out, raw=True).take_all()
    assert all(isinstance(r["data"], bytes) for r in raw)

    # corrupt framing is detected
    import glob
    shard = glob.glob(out + "/*.tfrecords")[0]
    blob = bytearray(open(shard, "rb").read())
    blob[4] ^= 0xFF  # flip a length byte
    open(shard, "wb").write(bytes(blob))
    with pytest.raises(Exception, match="crc|truncated"):
        rdata.read_tfrecords(out).take_all()
