"""Shared test fixtures.

Pattern from the reference's conftest (python/ray/tests/conftest.py:580
ray_start_regular, :497 shutdown_only): tests run against a real
single-node runtime. JAX tests run on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without TPU hardware (the
reference's analogue: fake NCCL groups / CPUCommunicator,
python/ray/experimental/channel/cpu_communicator.py).
"""

import os

# Must be set before jax is imported anywhere in the test process.
# Hard-set (not setdefault): the kernel env ships JAX_PLATFORMS=axon +
# PALLAS_AXON_POOL_IPS, which a sitecustomize hook turns into a TPU PJRT
# registration in EVERY python process — including spawned worker
# processes, whose rollout/train steps would then run over the TPU
# tunnel one RPC per step. Tests pin the whole process tree to the
# virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# Persistent compilation cache shared by the test process AND every
# spawned worker process (env inherits): each worker would otherwise
# re-jit identical tiny programs, which dominates suite wall time on
# this 1-core box. The dir is keyed by a host fingerprint: XLA:CPU AOT
# artifacts embed the compile machine's CPU features, and loading a
# cache populated on a different host (e.g. a container snapshot moved
# between machines) spews per-program feature-mismatch errors and
# recompiles — slower than no cache at all.


def _host_cache_dir() -> str:
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (ln for ln in f if ln.startswith("flags")), platform.processor()
            )
    except OSError:
        flags = platform.processor()
    fp = hashlib.sha256(
        (platform.machine() + str(flags)).encode()
    ).hexdigest()[:12]
    return f"/tmp/ray_tpu_jax_test_cache_{fp}"


os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _host_cache_dir())
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
os.environ.setdefault("RAY_TPU_NUM_TPUS", "0")
# XLA:CPU's AOT cache loader logs a full ERROR line per cached program
# whose embedded "machine features" include XLA's own tuning pseudo-
# features (+prefer-no-scatter/+prefer-no-gather) — harmless (it just
# recompiles) but it floods test logs. 3 = fatal-only for TSL/XLA logs.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

import jax

# The environment's PJRT plugin (axon) force-selects itself via
# jax.config at interpreter start, overriding JAX_PLATFORMS env; pin
# the config back to cpu so tests run on the virtual 8-device mesh.
jax.config.update("jax_platforms", "cpu")
# Same problem for the cache env vars: sitecustomize imported jax at
# interpreter start, before this file set the env — config-bound
# values were already baked, so set them on the config directly too
# (worker processes spawn with the env above and pick it up normally).
# Mirror whichever value won the setdefault, so a user-provided dir is
# respected in both the main process and workers.
jax.config.update(
    "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import signal

import pytest

# Per-test wall-clock cap (reference parity: pytest.ini timeout=180).
# SIGALRM-based so no extra dependency; pytest runs tests in the main
# thread, where the alarm is deliverable.
TEST_TIMEOUT_S = int(os.environ.get("RAY_TPU_TEST_TIMEOUT", "180"))


def _alarm_wrapped(phase):
    @pytest.hookimpl(hookwrapper=True)
    def hook(item):
        def _handler(signum, frame):
            raise TimeoutError(
                f"test {phase} exceeded {TEST_TIMEOUT_S}s timeout "
                f"(RAY_TPU_TEST_TIMEOUT)"
            )

        old = signal.signal(signal.SIGALRM, _handler)
        signal.alarm(TEST_TIMEOUT_S)
        try:
            yield
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)

    return hook


# Cover setup and teardown too — a hang in ray_tpu.init inside a fixture
# must be killed just like a hang in the test body (pytest-timeout parity).
pytest_runtest_setup = _alarm_wrapped("setup")
pytest_runtest_call = _alarm_wrapped("call")
pytest_runtest_teardown = _alarm_wrapped("teardown")


def pytest_configure(config):
    # tier-1 runs with -m 'not slow' under a hard suite-level timeout
    # (ROADMAP.md); "slow" marks long soaks and convergence tests that
    # stay runnable via a plain `pytest tests/` invocation.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 timed suite"
    )


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_4_cpus():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    import ray_tpu

    yield None
    ray_tpu.shutdown()
