"""Shared test fixtures.

Pattern from the reference's conftest (python/ray/tests/conftest.py:580
ray_start_regular, :497 shutdown_only): tests run against a real
single-node runtime. JAX tests run on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without TPU hardware (the
reference's analogue: fake NCCL groups / CPUCommunicator,
python/ray/experimental/channel/cpu_communicator.py).
"""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
os.environ.setdefault("RAY_TPU_NUM_TPUS", "0")

import jax

# The environment's PJRT plugin (axon) force-selects itself via
# jax.config at interpreter start, overriding JAX_PLATFORMS env; pin
# the config back to cpu so tests run on the virtual 8-device mesh.
jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_4_cpus():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=4, max_workers=4, ignore_reinit_error=True)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def shutdown_only():
    import ray_tpu

    yield None
    ray_tpu.shutdown()
