"""Mesh / sharded-train-step tests on the virtual 8-device CPU mesh."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu import parallel
from ray_tpu.models import llama


def test_make_mesh_default_all_fsdp():
    mesh = parallel.make_mesh()
    assert mesh.shape["fsdp"] == 8
    assert parallel.dp_degree(mesh) == 8


def test_make_mesh_explicit():
    mesh = parallel.make_mesh(data=2, model=2)
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 2
    assert mesh.shape["fsdp"] == 2  # auto axis absorbs the rest
    assert parallel.dp_degree(mesh) == 4


def test_make_mesh_indivisible_raises():
    with pytest.raises(ValueError):
        parallel.make_mesh(data=3)


def test_single_device_mesh():
    mesh = parallel.single_device_mesh()
    assert all(v == 1 for v in mesh.shape.values())


@pytest.fixture(scope="module")
def sharded_state():
    mesh = parallel.make_mesh(data=2, fsdp=2, model=2)
    cfg = llama.LLAMA_TINY
    opt = parallel.default_optimizer(1e-3, warmup_steps=2, total_steps=50)
    state, sh = parallel.create_train_state(
        mesh, jax.random.PRNGKey(0),
        lambda r: llama.init_params(r, cfg), opt, llama.param_specs(cfg),
    )
    return mesh, cfg, opt, state, sh


def test_params_are_sharded(sharded_state):
    mesh, cfg, opt, state, sh = sharded_state
    wq = state.params["blocks"]["wq"]
    spec = wq.sharding.spec
    # (L, D, H, hd) sharded (None, fsdp, model, None)
    assert spec == P(None, "fsdp", "model", None)
    # embed (V, D) sharded (model, fsdp)
    assert state.params["embed"].sharding.spec == P("model", "fsdp")


def test_opt_state_moments_shadow_param_sharding(sharded_state):
    mesh, cfg, opt, state, sh = sharded_state
    leaves = jax.tree_util.tree_leaves(state.opt_state)
    big = [l for l in leaves if l.ndim == 4]
    assert big, "expected adam moments with stacked-layer shapes"
    for l in big:
        assert any(ax in str(l.sharding.spec) for ax in ("fsdp", "model"))


def test_sharded_train_step_runs_and_learns(sharded_state):
    mesh, cfg, opt, _, sh = sharded_state
    # Fresh state: the train step donates its input state, which would
    # invalidate the module-scoped fixture's arrays for later tests.
    state, _ = parallel.create_train_state(
        mesh, jax.random.PRNGKey(7),
        lambda r: llama.init_params(r, cfg), opt, llama.param_specs(cfg),
    )
    step = parallel.make_train_step(
        partial(llama.loss_fn, config=cfg), opt, mesh, sh
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size)
    batch = {"tokens": tokens}
    losses = []
    for _ in range(10):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(m["step"]) == 10


def test_sharded_matches_single_device():
    """The GSPMD-sharded step must compute the same loss as 1-device."""
    cfg = llama.LLAMA_TINY
    opt = parallel.default_optimizer(1e-3, warmup_steps=2, total_steps=50)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size)
    batch = {"tokens": tokens}

    results = []
    for mesh in (
        parallel.make_mesh(data=2, fsdp=2, model=2),
        parallel.make_mesh(devices=jax.devices()[:1]),
    ):
        state, sh = parallel.create_train_state(
            mesh, jax.random.PRNGKey(0),
            lambda r: llama.init_params(r, cfg), opt, llama.param_specs(cfg),
        )
        step = parallel.make_train_step(
            partial(llama.loss_fn, config=cfg), opt, mesh, sh
        )
        state, m = step(state, batch)
        state, m2 = step(state, batch)
        results.append((float(m["loss"]), float(m2["loss"])))
    # bf16 activations: different mesh layouts reorder reductions.
    np.testing.assert_allclose(results[0], results[1], rtol=3e-2)


def test_eval_step(sharded_state):
    mesh, cfg, opt, state, sh = sharded_state
    ev = parallel.make_eval_step(partial(llama.loss_fn, config=cfg), mesh, sh)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (8, 33), 0, cfg.vocab_size)
    out = ev(state, {"tokens": tokens})
    assert np.isfinite(float(out["loss"]))
