"""Chaos fault injection: RAY_TPU_CHAOS_DROP drops inbound hub messages
by type/probability (reference: src/ray/rpc/rpc_chaos.h:23 driving flake
regression). The client's retransmit layer (idempotent requests resend
on reply loss — the analogue of the reference's retryable gRPC client)
must keep every path below correct under heavy drop rates."""

import os

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def chaos_runtime(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_CHAOS_DROP",
        "get:0.4,wait:0.4,kv_get:0.4,kv_put:0.4,pg_ready:0.4,"
        "stream_next:0.4,fetch_object:0.4",
    )
    # retransmit quickly so drop-heavy tests stay fast
    from ray_tpu._private.client import CoreClient

    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.2)
    ctx = ray_tpu.init(num_cpus=2, max_workers=2)
    yield ctx
    ray_tpu.shutdown()


def test_get_survives_drops(chaos_runtime):
    @ray_tpu.remote
    def f(i):
        return i * 2

    # many gets: with p=0.4 drop per request, ~40% need >=1 retransmit
    for batch in range(3):
        refs = [f.remote(i) for i in range(10)]
        assert ray_tpu.get(refs, timeout=60) == [i * 2 for i in range(10)]


def test_wait_survives_drops(chaos_runtime):
    @ray_tpu.remote
    def g():
        return "ok"

    refs = [g.remote() for _ in range(8)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=8, timeout=60)
    assert len(ready) == 8 and not not_ready


def test_kv_survives_drops(chaos_runtime):
    client = ray_tpu._private.worker.get_client()
    for i in range(20):
        assert client.kv_put(f"k{i}".encode(), f"v{i}".encode())
    for i in range(20):
        assert client.kv_get(f"k{i}".encode()) == f"v{i}".encode()


def test_actor_calls_survive_get_drops(chaos_runtime):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    vals = [ray_tpu.get(c.bump.remote(), timeout=60) for _ in range(15)]
    assert vals == list(range(1, 16))
    ray_tpu.kill(c)


def test_streaming_survives_drops(chaos_runtime):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    vals = [ray_tpu.get(r, timeout=60) for r in gen.remote(10)]
    assert vals == list(range(10))


def test_pg_ready_survives_drops(chaos_runtime):
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}])
    assert pg.wait(timeout_seconds=30)
    remove_placement_group(pg)
