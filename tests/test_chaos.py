"""Chaos fault injection (chaos.py): one seeded RAY_TPU_CHAOS_PLAN
drives message drop/delay/dup, timed conn/worker faults, partitions,
and mid-stream transfer death (reference: src/ray/rpc/rpc_chaos.h
driving flake regression; FoundationDB-style seeded schedules for
reproducibility). The legacy RAY_TPU_CHAOS_DROP env keeps working as an
alias — the first block of tests below still uses it deliberately. The
client's retransmit layer (idempotent requests resend with capped
exponential backoff on reply loss — the analogue of the reference's
retryable gRPC client) must keep every path below correct under heavy
drop rates."""

import os
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def chaos_runtime(monkeypatch):
    monkeypatch.setenv(
        "RAY_TPU_CHAOS_DROP",
        "get:0.4,wait:0.4,kv_get:0.4,kv_put:0.4,pg_ready:0.4,"
        "stream_next:0.4,fetch_object:0.4",
    )
    # retransmit quickly so drop-heavy tests stay fast
    from ray_tpu._private.client import CoreClient

    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.2)
    ctx = ray_tpu.init(num_cpus=2, max_workers=2)
    yield ctx
    ray_tpu.shutdown()


def test_get_survives_drops(chaos_runtime):
    @ray_tpu.remote
    def f(i):
        return i * 2

    # many gets: with p=0.4 drop per request, ~40% need >=1 retransmit
    for batch in range(3):
        refs = [f.remote(i) for i in range(10)]
        assert ray_tpu.get(refs, timeout=60) == [i * 2 for i in range(10)]


def test_wait_survives_drops(chaos_runtime):
    @ray_tpu.remote
    def g():
        return "ok"

    refs = [g.remote() for _ in range(8)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=8, timeout=60)
    assert len(ready) == 8 and not not_ready


def test_kv_survives_drops(chaos_runtime):
    client = ray_tpu._private.worker.get_client()
    for i in range(20):
        assert client.kv_put(f"k{i}".encode(), f"v{i}".encode())
    for i in range(20):
        assert client.kv_get(f"k{i}".encode()) == f"v{i}".encode()


def test_actor_calls_survive_get_drops(chaos_runtime):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    vals = [ray_tpu.get(c.bump.remote(), timeout=60) for _ in range(15)]
    assert vals == list(range(1, 16))
    ray_tpu.kill(c)


def test_streaming_survives_drops(chaos_runtime):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i

    vals = [ray_tpu.get(r, timeout=60) for r in gen.remote(10)]
    assert vals == list(range(10))


def test_pg_ready_survives_drops(chaos_runtime):
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}])
    assert pg.wait(timeout_seconds=30)
    remove_placement_group(pg)


# ------------------------------------------------------ plan grammar units


def test_plan_grammar_parses_every_fault_type():
    from ray_tpu._private.chaos import parse_plan

    p = parse_plan(
        "seed=7;drop:submit_task@0.05;delay:get@10ms-50ms;"
        "dup:put@0.2;delay:worker.exec@1s-2s@0.5;drop:client.get@0.3;"
        "conn_kill:client@t+2s;worker_kill:2@1.5s;worker_hang:1;"
        "partition:node2@3s-5s;close_after:2"
    )
    assert p.seed == 7
    kinds = [(r.kind, r.scope, r.msg_type) for r in p.rules]
    assert ("drop", "hub", "submit_task") in kinds
    assert ("delay", "hub", "get") in kinds
    assert ("delay", "worker", "exec") in kinds
    assert ("drop", "client", "get") in kinds
    delay = next(r for r in p.rules if r.msg_type == "get")
    assert (delay.lo, delay.hi) == (0.01, 0.05)
    timed = [(f.kind, f.at, f.count) for f in p.timed]
    # sorted by fire time; t+2s == 2s; worker_hang defaults to t=1s
    assert timed == [
        ("worker_hang", 1.0, 1), ("worker_kill", 1.5, 2),
        ("conn_kill", 2.0, 1),
    ]
    assert p.partitions == {"node2": [(3.0, 5.0)]}
    assert p.close_after == 2


def test_plan_rejects_malformed_directives():
    from ray_tpu._private.chaos import PlanError, parse_plan

    for bad in ("seed=x", "drop:get@nope", "delay:get", "frobnicate:1",
                "partition:node1", "conn_kill:hub@1s",
                "delay:get@5s-1s", "delay:get@1ms-2ms@oops",
                "drop:worker.exec@0.5", "dup:worker.exec@1"):
        with pytest.raises(PlanError):
            parse_plan(bad)


def test_legacy_aliases_translate(monkeypatch):
    from ray_tpu._private import chaos

    monkeypatch.delenv("RAY_TPU_CHAOS_PLAN", raising=False)
    monkeypatch.setenv("RAY_TPU_CHAOS_DROP", "get:0.4,wait:0.2")
    monkeypatch.setenv("RAY_TPU_CHAOS_OBJECT_AGENT", "close_after:3")
    hub_eng = chaos.engine_for("hub")
    assert hub_eng is not None
    assert {mt for mt in hub_eng.rules} == {"get", "wait"}
    agent_eng = chaos.engine_for("object_agent")
    assert agent_eng is not None and agent_eng.close_after == 3
    # scopes with nothing to inject stay fully inert (None)
    assert chaos.engine_for("client") is None
    assert chaos.engine_for("worker") is None


def test_engine_decisions_and_schedule_are_deterministic():
    """Same seed -> identical fault schedule AND identical per-message
    decision sequence; a different seed diverges."""
    from ray_tpu._private.chaos import ChaosEngine

    plan = ("seed=42;drop:get@0.5;delay:put@1ms-9ms@0.5;"
            "worker_kill:1@1s;conn_kill:client@2s")
    msgs = ["get", "put", "get", "get", "put", "get"] * 20
    a = ChaosEngine(plan, "hub")
    b = ChaosEngine(plan, "hub")
    acts_a = [a.message_action(m) for m in msgs]
    acts_b = [b.message_action(m) for m in msgs]
    assert acts_a == acts_b
    assert [(f.kind, f.at) for f in a.timed] == [
        ("worker_kill", 1.0), ("conn_kill", 2.0)
    ]
    c = ChaosEngine(plan.replace("seed=42", "seed=43"), "hub")
    assert [c.message_action(m) for m in msgs] != acts_a
    # sibling scopes draw from independent streams: consuming worker
    # draws must not shift the hub's sequence
    w = ChaosEngine("seed=42;delay:worker.exec@1ms-2ms", "worker")
    assert w.rules and "exec" in w.rules


def test_retry_delay_backoff_unit():
    """Capped exponential backoff with full jitter (GL011's fix shape):
    the step doubles to the cap; each wait lands in [step/2, step]."""
    from ray_tpu._private.client import CoreClient

    class Probe:
        _RETRY_PERIOD_S = 0.2
        _RETRY_MAX_S = 3.0
        _retry_delay = CoreClient._retry_delay

    p = Probe()
    delay = p._RETRY_PERIOD_S
    steps = []
    for _ in range(8):
        waited, nxt = p._retry_delay(delay)
        assert delay * 0.5 <= waited <= delay
        steps.append(delay)
        delay = nxt
    assert steps[:5] == [0.2, 0.4, 0.8, 1.6, 3.0]
    assert delay == 3.0  # capped


# --------------------------------------------------- plan-driven runtimes


@pytest.fixture
def plan_runtime(monkeypatch):
    """Runtime factory: set a chaos plan (and friends) BEFORE init —
    the hub reads the env at construction, workers inherit it."""
    from ray_tpu._private.client import CoreClient

    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.2)

    def start(plan, **env):
        monkeypatch.setenv("RAY_TPU_CHAOS_PLAN", plan)
        for k, v in env.items():
            monkeypatch.setenv(k, str(v))
        return ray_tpu.init(num_cpus=2, max_workers=2)

    yield start
    ray_tpu.shutdown()


def _events():
    from ray_tpu._private import worker

    return worker.get_client().list_state("events")


def test_plan_drop_and_delay_survive(plan_runtime):
    plan_runtime("seed=1;drop:get@0.4;delay:wait@1ms-10ms;dup:kv_put@1")

    @ray_tpu.remote
    def f(i):
        return i * 2

    refs = [f.remote(i) for i in range(10)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=10, timeout=60)
    assert len(ready) == 10 and not not_ready
    assert ray_tpu.get(refs, timeout=60) == [i * 2 for i in range(10)]
    client = ray_tpu._private.worker.get_client()
    # dup: the duplicated idempotent write must not corrupt anything
    for i in range(5):
        assert client.kv_put(f"k{i}".encode(), f"v{i}".encode())
        assert client.kv_get(f"k{i}".encode()) == f"v{i}".encode()
    kinds = {e["kind"] for e in _events()}
    assert "chaos_dup" in kinds


def test_client_scope_outbound_drop(plan_runtime):
    """drop:client.get — the CLIENT discards its own outbound GETs;
    the backoff retransmit layer must still converge."""
    plan_runtime("seed=2;drop:client.get@0.5")
    from ray_tpu._private import worker

    assert worker.get_client()._chaos is not None

    @ray_tpu.remote
    def g(i):
        return i + 7

    assert ray_tpu.get([g.remote(i) for i in range(8)], timeout=60) == [
        i + 7 for i in range(8)
    ]


def test_worker_hang_then_timeout_kills_and_retries(plan_runtime):
    """The satellite regression: chaos SIGSTOPs a busy worker; the
    per-task options(timeout_s=...) deadline kills the stalled execute
    and the retry completes on a fresh worker."""
    plan_runtime("seed=5;worker_hang:1@0.6s")

    @ray_tpu.remote(max_retries=2)
    def slow(i):
        time.sleep(1.0)
        return i + 50

    refs = [slow.options(timeout_s=2.0).remote(i) for i in range(3)]
    assert ray_tpu.get(refs, timeout=90) == [50, 51, 52]
    evs = _events()
    kinds = [e["kind"] for e in evs]
    assert "chaos_worker_hang" in kinds
    assert "task_timeout" in kinds
    assert any(
        e["kind"] == "task_retry" and e.get("reason") == "timeout"
        for e in evs
    )


def test_worker_hang_reaches_agent_spawned_workers(monkeypatch):
    """Chaos worker faults must reach workers whose proc handle lives
    with a node AGENT, not the hub (remote SIGSTOP/SIGKILL rides
    P.KILL_WORKER's sig field): with every worker on an agent node the
    fault still fires and the watchdog still recovers the stall."""
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_CHAOS_PLAN", "seed=11;worker_hang:1@1s")
    # above the 1.2s sleep so only the STALLED attempt trips it
    monkeypatch.setenv("RAY_TPU_TASK_TIMEOUT_DEFAULT_S", "2.5")
    cluster = Cluster(head_num_cpus=0)
    try:
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=1, max_retries=2)
        def slow(i):
            time.sleep(1.2)
            return i * 3

        refs = [slow.remote(i) for i in range(2)]
        assert ray_tpu.get(refs, timeout=90) == [0, 3]
        evs = _events()
        hangs = [e for e in evs if e["kind"] == "chaos_worker_hang"]
        assert hangs, "worker_hang never fired with agent-only workers"
        assert all(e.get("node_id") == "node1" for e in hangs), hangs
    finally:
        cluster.shutdown()


def test_task_timeout_gives_up_past_retry_budget(plan_runtime):
    plan_runtime("")  # no chaos: the watchdog alone

    @ray_tpu.remote(max_retries=0)
    def stuck():
        time.sleep(60)

    ref = stuck.options(timeout_s=0.5).remote()
    with pytest.raises(ray_tpu.exceptions.TaskTimeoutError):
        ray_tpu.get(ref, timeout=30)
    assert any(e["kind"] == "task_timeout" for e in _events())


def test_actor_call_timeout_kills_and_restarts(plan_runtime):
    """Actor calls get the execute deadline too: a hung actor worker
    never EOFs, so the timeout kill is the only recovery — in-flight
    callers see ActorDiedError and the actor restarts per budget."""
    plan_runtime("")  # no chaos: the deadline machinery alone

    @ray_tpu.remote(max_restarts=1)
    class S:
        def stall(self):
            time.sleep(60)

        def ok(self):
            return "ok"

    s = S.remote()
    ref = s.stall.options(timeout_s=0.5).remote()
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(ref, timeout=30)
    # the restarted incarnation serves later calls
    assert ray_tpu.get(s.ok.remote(), timeout=30) == "ok"
    evs = _events()
    assert any(
        e["kind"] == "task_timeout" and e.get("actor_id") for e in evs
    )
    assert any(e["kind"] == "actor_restart" for e in evs)


def test_chaos_state_and_inert_default(plan_runtime):
    plan_runtime("seed=4;drop:get@0.2;worker_kill:1@50ms")

    @ray_tpu.remote
    def f():
        time.sleep(0.3)
        return 1

    assert ray_tpu.get([f.remote() for _ in range(3)], timeout=60) == [1] * 3
    from ray_tpu._private import worker

    rows = worker.get_client().list_state("chaos")
    assert rows and rows[0]["plan"].startswith("seed=4")
    assert rows[0]["counts"].get("worker_kill") == 1
    assert any(r.get("kind") == "chaos_worker_kill" for r in rows[1:])


def test_chaos_survives_sharded_hub(plan_runtime, monkeypatch):
    """Both control-plane topologies share the injection seam: with 4
    reactor shards, drops hit the state plane's dispatch and a
    conn_kill:worker expels through the owning shard's ring API."""
    monkeypatch.setenv("RAY_TPU_HUB_SHARDS", "4")
    plan_runtime("seed=6;drop:get@0.3;conn_kill:worker@0.5s")

    @ray_tpu.remote(max_retries=2)
    def f(i):
        time.sleep(0.2)
        return i * 11

    refs = [f.remote(i) for i in range(8)]
    assert ray_tpu.get(refs, timeout=90) == [i * 11 for i in range(8)]
    kinds = [e["kind"] for e in _events()]
    assert "chaos_conn_kill" in kinds
    assert "worker_exit" in kinds  # the expelled worker died cleanly


def test_bulk_submit_survives_drop_and_dup(plan_runtime):
    """The vectorized SUBMIT_TASKS frame rides the same retransmit
    contract as unary requests: a dropped frame is resent by
    _scan_unacked after the ack deadline, and a duplicated (or
    replayed) frame is absorbed by the hub's per-task dedup
    (_task_event_index) — every task runs exactly once, results land
    in submission order."""
    plan_runtime("seed=13;drop:submit_tasks@0.5;dup:submit_tasks@0.5;"
                 "drop:get@0.3")

    @ray_tpu.remote
    def f(i):
        return i * 7

    for _wave in range(3):
        refs = f.map(list(range(12)))
        assert ray_tpu.get(refs, timeout=90) == [i * 7 for i in range(12)]


def test_chaos_cli_renders(plan_runtime, monkeypatch, capsys):
    plan_runtime("seed=8;drop:get@0.2;worker_kill:1@100ms")

    @ray_tpu.remote
    def f():
        time.sleep(0.3)
        return 1

    assert ray_tpu.get([f.remote() for _ in range(3)], timeout=60) == [1] * 3
    # _connect reuses the live in-process runtime (ignore_reinit_error)
    monkeypatch.setenv("RAY_TPU_ADDRESS", "in-process")
    from ray_tpu import scripts

    scripts.main(["chaos"])
    out = capsys.readouterr().out
    assert "plan: seed=8" in out
    assert "worker_kill" in out
    scripts.main(["chaos", "--format", "json"])
    import json as _json

    rows = _json.loads(capsys.readouterr().out)
    assert rows and rows[0]["seed"] == 8


def test_no_plan_is_inert(ray_start_regular):
    """With no plan, every injection point is a cached None and
    list_state("chaos") is empty."""
    from ray_tpu._private import worker

    assert worker._hub._chaos is None
    assert worker.get_client()._chaos is None
    assert worker.get_client().list_state("chaos") == []


def test_delayed_redelivery_to_dead_conn_is_dropped(plan_runtime):
    """Regression: a frame parked by delay: whose conn disconnects
    inside the delay window must NOT replay when the timer fires —
    stateful handlers (_on_hello) would re-register the dead conn in
    client_conns, and with no second CONN_LOST ever pruning it the
    phantom entry becomes the deterministic oldest-first conn_kill
    victim. Both topologies close the conn in _safe_disconnect, so
    closed-ness IS the disconnect signal the redelivery checks."""
    plan_runtime("seed=1;drop:__unused__@1")  # any plan: live hub engine
    from ray_tpu._private import worker

    hub = worker._hub

    class DeadConn:
        closed = True

    before = len(hub.client_conns)
    hub._dispatch_after_chaos(DeadConn(), "hello", {"role": "client"})
    assert len(hub.client_conns) == before, "dead conn re-registered"


def test_get_retransmit_span_dedup_under_backoff(monkeypatch):
    """PR 8 span-dedup under the new cadence: a get parked on a slow
    task retransmits on the backoff schedule (fast base here -> several
    resends), yet the hub emits exactly ONE hub.get span — the
    _inflight_reqs dedup is keyed on (conn, req_id), not cadence."""
    from ray_tpu._private.client import CoreClient

    monkeypatch.setenv("RAY_TPU_TRACING", "1")
    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.05)
    ray_tpu.init(num_cpus=2, max_workers=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def slow():
            time.sleep(1.2)
            return "v"

        ref = slow.remote()
        assert ray_tpu.get(ref, timeout=60) == "v"
        from ray_tpu._private import worker

        client = worker.get_client()
        deadline = time.monotonic() + 10
        spans = []
        while time.monotonic() < deadline:
            for row in client.list_state("traces"):
                s = client.list_state("traces", trace_id=row["trace_id"])
                if any(sp.get("name") == "hub.get" for sp in s):
                    spans = s
                    break
            if spans:
                break
            time.sleep(0.1)
        assert spans, "no traced get landed"
        n_get = sum(1 for sp in spans if sp.get("name") == "hub.get")
        assert n_get == 1, f"expected 1 hub.get span, got {n_get}"
    finally:
        ray_tpu.shutdown()


def test_fetch_retransmit_during_reconstruction_parks(monkeypatch):
    """Regression (soak flake): the backoff retransmit of a FETCH_OBJECT
    that triggered a lineage rerun re-enters the hub while the object's
    entry is marked not-ready for the reconstruction window. It must
    park beside the original waiter — the old code replied "no such
    segment" and the client surfaced ObjectLostError mid-recovery. The
    fast retransmit base + the slow rerun guarantee several retransmits
    land inside the window. FETCH_CHUNK is shrunk so the relay pull is
    multi-chunk: the parked request must replay with its offset/length
    intact (a bare req_id replay answers a chunk request with
    whole-object bytes and corrupts the reassembled segment)."""
    from ray_tpu._private.client import CoreClient
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setattr(CoreClient, "_RETRY_PERIOD_S", 0.05)
    monkeypatch.setattr(CoreClient, "FETCH_CHUNK", 65536)
    cluster = Cluster(head_num_cpus=2)
    try:
        node = cluster.add_node(num_cpus=2, resources={"eph": 4.0})

        @ray_tpu.remote(resources={"eph": 1.0}, max_retries=2)
        def make():
            time.sleep(0.5)  # the rerun holds the window open
            return np.arange(80_000, dtype=np.float64)  # shm segment

        ref = make.remote()
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
        assert ready
        cluster.remove_node(node)
        cluster.add_node(num_cpus=2, resources={"eph": 4.0})
        # fetch fails -> reconstruction parks it; retransmits at ~25-50ms
        # must park too (idempotent per req_id), not error out — and the
        # 10-chunk reassembly must be byte-exact through the replay
        arr = ray_tpu.get(ref, timeout=60)
        assert np.array_equal(arr, np.arange(80_000, dtype=np.float64))
        from ray_tpu._private import worker

        hub = worker._hub
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
            hub._reconstruct_waiters or hub._reconstructing
        ):
            time.sleep(0.1)
        assert not hub._reconstruct_waiters, "parked fetches leaked"
        assert not hub._reconstructing, "reconstruction flag leaked"
    finally:
        cluster.shutdown()


# --------------------------------------------------------------- serve verbs


def test_serve_verbs_parse():
    from ray_tpu._private.chaos import parse_plan

    p = parse_plan(
        "seed=5;replica_kill:llm@2s;replica_kill:vit;"
        "slow_replica:vit@10ms-50ms@0.5;route_partition:llm@1s-3s"
    )
    timed = [(f.kind, f.arg, f.at) for f in p.timed]
    # replica_kill defaults to t=1s, schedule sorted by fire time
    assert timed == [
        ("replica_kill", "vit", 1.0), ("replica_kill", "llm", 2.0),
    ]
    slow = next(r for r in p.rules if r.kind == "slow_replica")
    assert (slow.scope, slow.msg_type, slow.prob) == ("serve", "vit", 0.5)
    assert (slow.lo, slow.hi) == (0.01, 0.05)
    assert p.route_partitions == {"llm": [(1.0, 3.0)]}


def test_serve_verbs_reject_malformed():
    from ray_tpu._private.chaos import PlanError, parse_plan

    for bad in ("replica_kill:", "slow_replica:llm", "slow_replica:@1ms-2ms",
                "slow_replica:llm@5s-1s", "slow_replica:llm@1ms-2ms@oops",
                "route_partition:llm", "route_partition:@1s-2s",
                "route_partition:llm@3s-1s"):
        with pytest.raises(PlanError):
            parse_plan(bad)


def test_serve_verbs_are_scope_filtered():
    """Serve-plane faults live only in serve-scope engines: the hub
    scope must not see replica_kill in its timed schedule, and the
    serve scope must not inherit hub timed faults or node partitions."""
    from ray_tpu._private.chaos import ChaosEngine

    plan = ("seed=9;replica_kill:llm@2s;slow_replica:llm@1ms-2ms;"
            "route_partition:llm@1s-3s;worker_kill:1@1s;"
            "partition:node2@3s-5s;drop:get@0.5")
    serve = ChaosEngine(plan, "serve")
    assert [(f.kind, f.arg) for f in serve.timed] == [("replica_kill", "llm")]
    assert set(serve.slow_rules) == {"llm"}
    assert set(serve.route_partitions) == {"llm"}
    assert not serve.rules and not serve.partitions
    hub = ChaosEngine(plan, "hub")
    assert [f.kind for f in hub.timed] == ["worker_kill"]
    assert not hub.slow_rules and not hub.route_partitions
    assert set(hub.rules) == {"get"}
    assert set(hub.partitions) == {"node2"}


def test_serve_scope_inert_without_serve_verbs(monkeypatch):
    from ray_tpu._private import chaos

    monkeypatch.setenv("RAY_TPU_CHAOS_PLAN", "seed=1;drop:get@0.5")
    monkeypatch.delenv("RAY_TPU_CHAOS_DROP", raising=False)
    monkeypatch.delenv("RAY_TPU_CHAOS_OBJECT_AGENT", raising=False)
    assert chaos.engine_for("serve") is None
    monkeypatch.setenv(
        "RAY_TPU_CHAOS_PLAN", "seed=1;slow_replica:llm@1ms-2ms"
    )
    eng = chaos.engine_for("serve")
    assert eng is not None and set(eng.slow_rules) == {"llm"}


def test_serve_draws_are_deterministic():
    """Same (seed, scope) -> identical slow_replica delay sequence and
    identical partition windows; a different seed diverges."""
    from ray_tpu._private.chaos import ChaosEngine

    plan = "seed=42;slow_replica:llm@1ms-20ms@0.7;route_partition:llm@1s-2s"
    a = ChaosEngine(plan, "serve")
    b = ChaosEngine(plan, "serve")
    seq_a = [a.execute_delay("llm") for _ in range(40)]
    seq_b = [b.execute_delay("llm") for _ in range(40)]
    assert seq_a == seq_b
    assert any(d > 0 for d in seq_a) and any(d == 0.0 for d in seq_a)
    c = ChaosEngine(plan.replace("seed=42", "seed=43"), "serve")
    assert [c.execute_delay("llm") for _ in range(40)] != seq_a
    # unknown deployment never draws (and never shifts the rng)
    d_eng = ChaosEngine(plan, "serve")
    assert d_eng.execute_delay("other") == 0.0
    assert [d_eng.execute_delay("llm") for _ in range(40)] == seq_a
    # window check is pure elapsed-time arithmetic once armed
    a.arm(now=100.0)
    assert not a.route_partition_active("llm", now=100.5)
    assert a.route_partition_active("llm", now=101.5)
    assert not a.route_partition_active("llm", now=102.5)
    assert not a.route_partition_active("other", now=101.5)
