"""Out-of-band object plane (PR 6): ownership directory + direct
peer<->peer transfer (object_agent.py), hub-relay fallback under
chaos, PUT_CHUNK replay idempotence, and readiness-push wait().

Reference analogues: src/ray/object_manager/ (direct push/pull between
stores, never through the GCS), core_worker reference_count.h
(ownership directory), and the core worker's local-store ready
callbacks (vs polling) for wait().
"""

import os
import tempfile
import time
import uuid

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import protocol as P


BIG = 20 * 1024 * 1024  # > 2 FETCH_CHUNKs, so transfers are multi-chunk


def _scratch_client(hub, hostname=None):
    """A shm-less CoreClient with a private scratch store — the
    in-process stand-in for ray_tpu.init(address=...) client mode."""
    from ray_tpu._private.client import CoreClient

    scratch = os.path.join(
        tempfile.gettempdir(), f"rt_plane_{uuid.uuid4().hex[:8]}"
    )
    os.makedirs(scratch, exist_ok=True)
    cl = CoreClient(
        hub.addr, scratch, role="client",
        worker_id=f"client_{uuid.uuid4().hex[:6]}",
    )
    cl.inline_only = True
    if hostname is not None:
        # defeat the same-host file-copy shortcut so the SOCKET path
        # is exercised on this single-machine test box
        cl.hostname = hostname
    return cl


@pytest.fixture
def runtime():
    ctx = ray_tpu.init(num_cpus=2, max_workers=2)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def chaos_agent_runtime(monkeypatch):
    # every agent connection dies after serving/accepting ONE chunk:
    # the "serving peer dies mid-transfer" scenario
    monkeypatch.setenv("RAY_TPU_CHAOS_OBJECT_AGENT", "close_after:1")
    ctx = ray_tpu.init(num_cpus=2, max_workers=2)
    yield ctx
    ray_tpu.shutdown()


def _hub():
    return ray_tpu._private.worker._hub


def _fallback_events(hub):
    return [e for e in hub.events if e["kind"] == "object_transfer_fallback"]


# ---------------------------------------------------------------- direct path
def test_direct_put_and_fetch_over_socket(runtime):
    hub = _hub()
    assert hub.object_agent is not None, "head object agent should be on"
    cl = _scratch_client(hub, hostname="elsewhere-host")
    try:
        big = np.random.randint(0, 256, (BIG,), dtype=np.uint8)
        # put: client-mode bytes stream straight to the head agent
        oid = cl.put_value(big)
        from ray_tpu._private import worker as w

        got = w.get_client().get([oid])[0]
        assert (got == big).all()
        assert hub.object_agent.stats()["bytes_received"] >= BIG
        # fetch: a driver-owned segment pulled over the agent socket
        ref = ray_tpu.put(big + 1)
        vals = cl.get([ref._id])
        assert (vals[0] == big + 1).all()
        assert hub.object_agent.stats()["bytes_served"] >= BIG
        assert not _fallback_events(hub), "direct path must not fall back"
        # location cached, then invalidated by the free broadcast
        assert ref._id.binary() in cl._resolve_cache
        ray_tpu.free([ref])
        deadline = time.time() + 5
        while ref._id.binary() in cl._resolve_cache and time.time() < deadline:
            time.sleep(0.05)
        assert ref._id.binary() not in cl._resolve_cache
    finally:
        cl.close()


def test_same_host_fetch_uses_file_copy(runtime):
    """A consumer on the producer's machine copies the segment file
    directly — no sockets, no hub bytes."""
    hub = _hub()
    cl = _scratch_client(hub)  # real hostname: matches the head's
    try:
        big = np.random.randint(0, 256, (BIG,), dtype=np.uint8)
        ref = ray_tpu.put(big)
        served_before = hub.object_agent.stats()["bytes_served"]
        vals = cl.get([ref._id])
        assert (vals[0] == big).all()
        assert hub.object_agent.stats()["bytes_served"] == served_before
        assert not _fallback_events(hub)
    finally:
        cl.close()


def test_direct_bytes_metric_exported(runtime):
    hub = _hub()
    cl = _scratch_client(hub, hostname="elsewhere-host")
    try:
        big = np.random.randint(0, 256, (BIG,), dtype=np.uint8)
        ref = ray_tpu.put(big)
        cl.get([ref._id])
        deadline = time.time() + 10  # next head heartbeat samples stats
        key = ("ray_tpu_object_direct_bytes", (("node_id", "node0"),))
        while time.time() < deadline:
            m = hub.metrics.get(key)
            if m is not None and m["value"] >= BIG:
                break
            time.sleep(0.2)
        assert hub.metrics.get(key) is not None
        assert hub.metrics[key]["value"] >= BIG
    finally:
        cl.close()


# ----------------------------------------------------- chaos: mid-stream death
def test_agent_death_mid_fetch_falls_back_to_relay(chaos_agent_runtime):
    hub = _hub()
    cl = _scratch_client(hub, hostname="elsewhere-host")
    try:
        big = np.random.randint(0, 256, (BIG,), dtype=np.uint8)
        ref = ray_tpu.put(big)
        vals = cl.get([ref._id])  # agent dies after chunk 1 of >=3
        assert (vals[0] == big).all(), "fallback value corrupted"
        evs = _fallback_events(hub)
        assert any(e["op"] == "fetch" for e in evs)
        m = hub.metrics.get(("ray_tpu_object_fallbacks_total", ()))
        assert m is not None and m["value"] >= 1
    finally:
        cl.close()


def test_agent_death_mid_put_falls_back_to_relay(chaos_agent_runtime):
    hub = _hub()
    cl = _scratch_client(hub, hostname="elsewhere-host")
    try:
        big = np.random.randint(0, 256, (BIG,), dtype=np.uint8)
        oid = cl.put_value(big)  # direct put dies -> PUT_CHUNK relay
        from ray_tpu._private import worker as w

        got = w.get_client().get([oid])[0]
        assert (got == big).all()
        assert any(e["op"] == "put" for e in _fallback_events(hub))
    finally:
        cl.close()


# ------------------------------------------------- PUT_CHUNK replay idempotence
def test_put_chunk_replay_is_idempotent(tmp_path):
    """A retransmitted chunk (reply-loss replay) — including a
    duplicate `last: True` — must neither corrupt the segment nor
    double-advance the hub-side size accounting."""
    from ray_tpu._private.hub import Hub

    hub = Hub(str(tmp_path / "sess"), resources={"CPU": 1.0})
    try:
        conn = object()  # only identity + outbox key are used
        oid = b"replay-test-oid"
        name = "replayseg"
        payload = os.urandom(64)
        mid = os.urandom(32)
        tail = os.urandom(16)

        def chunk(offset, data, last=False):
            hub._on_put_chunk(conn, {
                "object_id": oid, "name": name,
                "offset": offset, "data": data, "last": last,
            })

        chunk(0, payload)
        chunk(64, mid)
        chunk(64, mid)            # replayed middle chunk
        chunk(96, tail, last=True)
        e = hub.objects[oid]
        assert e.ready and e.kind == P.VAL_SHM and e.size == 112
        path = os.path.join(hub.session_dir, "objects", name)
        with open(path, "rb") as f:
            assert f.read() == payload + mid + tail
        # duplicate last-chunk replay after completion: dropped whole
        chunk(96, tail, last=True)
        assert hub.objects[oid].size == 112
        with open(path, "rb") as f:
            assert f.read() == payload + mid + tail
        assert not hub._client_puts, "replay must not reopen the stream"
    finally:
        hub._running = False
        if hub.object_agent is not None:
            hub.object_agent.close()
        hub.listener.close()


# ------------------------------------------------------------- readiness push
def test_wait_pop_loop_uses_readiness_push(runtime):
    from ray_tpu._private import worker as w

    client = w.get_client()
    pushed = []
    orig = client._on_ready_push
    client._inbound_handlers[P.READY_PUSH] = lambda p: (
        pushed.extend(p.get("ready", ())), orig(p)
    )

    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(100)]
    seen = set()
    not_ready = refs
    while not_ready:
        ready, not_ready = ray_tpu.wait(not_ready, timeout=60)
        seen.update(r._id.binary() for r in ready)
    assert len(seen) == 100
    assert pushed, "pop-loop should be served by READY_PUSH"
    # subscriptions drained: nothing left registered hub-side
    hub = _hub()
    deadline = time.time() + 5
    while hub._ready_watchers and time.time() < deadline:
        time.sleep(0.05)
    assert not hub._ready_watchers


def test_wait_all_and_timeout_semantics(runtime):
    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(50)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=50, timeout=60)
    assert len(ready) == 50 and not not_ready

    @ray_tpu.remote
    def never():
        time.sleep(600)

    stuck = never.remote()
    t0 = time.monotonic()
    ready, not_ready = ray_tpu.wait([stuck], timeout=0.3)
    assert not ready and not_ready == [stuck]
    assert time.monotonic() - t0 < 5
    # timeout=0: one non-blocking snapshot
    ready, not_ready = ray_tpu.wait([stuck], timeout=0)
    assert not ready and not_ready == [stuck]
    ray_tpu.cancel(stuck, force=True)


def test_wait_mixed_ready_ordering(runtime):
    """Ready quota is filled in id order; extras stay in not_ready even
    when already complete (Ray wait() contract)."""
    done = [ray_tpu.put(i) for i in range(4)]
    ready, not_ready = ray_tpu.wait(done, num_returns=2, timeout=30)
    assert len(ready) == 2 and len(not_ready) == 2
    assert [r._id for r in ready] == [r._id for r in done[:2]]


# ------------------------------------------------------ cluster invalidation
def test_node_down_invalidates_resolve_cache(shutdown_only):
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_num_cpus=2)
    try:
        node = cluster.add_node(num_cpus=2, resources={"away": 4.0})

        @ray_tpu.remote(resources={"away": 1.0})
        def make():
            return np.arange(500_000, dtype=np.float64)

        ref = make.remote()
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
        assert ready
        from ray_tpu._private import worker as w

        client = w.get_client()
        info = client._resolve_object(ref._id.binary())
        assert info is not None and info["node_id"] == node.node_id
        assert ref._id.binary() in client._resolve_cache
        cluster.remove_node(node)
        deadline = time.time() + 10
        while (
            ref._id.binary() in client._resolve_cache
            and time.time() < deadline
        ):
            time.sleep(0.1)
        assert ref._id.binary() not in client._resolve_cache, (
            "stale location survived node death"
        )
    finally:
        cluster.shutdown()
