"""Native (C++) shm ring channel (_native/ring_channel.cpp) and its
integration behind ShmChannel (experimental/channel/shm_channel.py)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.experimental.channel import ShmChannel


def _native_available() -> bool:
    from ray_tpu._native import ring_native

    return ring_native() is not None


pytestmark = pytest.mark.skipif(
    not _native_available(), reason="no C++ toolchain for _ring_native"
)


def test_default_backend_is_native():
    ch = ShmChannel.create(shape=(4,), dtype="float32")
    try:
        assert ch.backend == "native"
    finally:
        ch.close(unlink=True)


def test_native_roundtrip_and_order():
    ch = ShmChannel.create(shape=(8,), dtype="int64", capacity=3)
    try:
        for i in range(10):
            ch.write(np.full(8, i, np.int64), timeout_s=5)
            out = ch.read(timeout_s=5)
            assert out[0] == i
    finally:
        ch.close(unlink=True)


def test_native_blocking_full_and_empty():
    ch = ShmChannel.create(shape=(1,), dtype="int8", capacity=1)
    try:
        assert ch.try_read() is None
        ch.write(np.zeros(1, np.int8))
        with pytest.raises(TimeoutError):
            ch.write(np.zeros(1, np.int8), timeout_s=0.1)
        assert ch.try_read() is not None
        with pytest.raises(TimeoutError):
            ch.read(timeout_s=0.1)
    finally:
        ch.close(unlink=True)


def test_native_cross_process(ray_start_regular):
    """Descriptor pickles into a worker; both ends see one ring."""
    ch = ShmChannel.create(shape=(16,), dtype="float32", capacity=2)

    @ray_tpu.remote
    def producer(chan, n):
        for i in range(n):
            chan.write(np.full(16, float(i), np.float32), timeout_s=30)
        return n

    try:
        ref = producer.remote(ch, 5)
        got = [float(ch.read(timeout_s=30)[0]) for _ in range(5)]
        assert got == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert ray_tpu.get(ref) == 5
    finally:
        ch.close(unlink=True)


def test_py_backend_forced_and_pinned():
    ch = ShmChannel.create(shape=(4,), dtype="float32", backend="py")
    try:
        assert ch.backend == "py"
        import pickle

        ch2 = pickle.loads(pickle.dumps(ch))
        assert ch2.backend == "py"
        ch.write(np.arange(4, dtype=np.float32))
        assert ch2.read(timeout_s=5)[2] == 2.0
        ch2.close()
    finally:
        ch.close(unlink=True)


def test_native_latency_smoke():
    """Self ping-pong median latency should be far under the python
    ring's 500us poll floor (informational guard, generous bound)."""
    ch = ShmChannel.create(shape=(64,), dtype="float32", capacity=2)
    arr = np.zeros(64, np.float32)
    try:
        ch.write(arr)
        ch.read()  # warm
        lat = []
        for _ in range(200):
            t0 = time.perf_counter()
            ch.write(arr)
            ch.read()
            lat.append(time.perf_counter() - t0)
        med = sorted(lat)[len(lat) // 2]
        assert med < 0.005, f"native round-trip median {med*1e6:.0f}us"
    finally:
        ch.close(unlink=True)
