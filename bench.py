"""Headline benchmark: Llama training throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "platform": ..., "vs_baseline": N}

The reference publishes no LLM-training numbers (BASELINE.md: north-star
targets "to be established by our harness"), so ``vs_baseline`` is
hardware-normalized: measured MFU divided by 0.50 — the MFU an
A100-class baseline (the north star's comparison hardware) typically
sustains on dense decoder training. vs_baseline >= 1.0 means we extract
at least as much of the silicon as the reference stack would.

Model: ~1.1B-param Llama (TinyLlama shape), bf16 params, remat on,
seq 2048 — big enough that MXU utilization is meaningful on one chip,
small enough to fit one v5e's 16 GiB HBM with Adam state.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from functools import partial


# bf16 peak TFLOPs per chip by TPU generation (public spec sheets).
PEAK_TFLOPS = {"v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6e": 918.0}


def _detect_peak() -> float:
    import os

    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for key, val in PEAK_TFLOPS.items():
        if key in gen:
            return val
    return PEAK_TFLOPS["v5e"]


def _ensure_live_backend() -> None:
    """The TPU arrives over a tunnel (axon PJRT); if the tunnel is
    wedged, jax.devices() blocks forever. Probe it (shared helper,
    subprocess + hard timeout) and fall back to CPU rather than
    hanging the whole bench run."""
    import sys as _sys

    import os

    from ray_tpu._private.jax_utils import probe_accelerator

    platform, _ = probe_accelerator(
        timeout_s=float(os.environ.get("RAY_TPU_BENCH_PROBE_TIMEOUT", "120")),
        force=True,
    )
    if platform in ("tpu", "axon"):
        return
    import jax

    print(
        f"bench: accelerator probe returned {platform!r}; "
        "falling back to CPU",
        file=_sys.stderr,
    )
    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    _ensure_live_backend()
    import jax
    import jax.numpy as jnp

    from ray_tpu import parallel
    from ray_tpu.models import llama

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        cfg = dataclasses.replace(llama.LLAMA_TINY)
        batch, seq, steps = 4, 128, 3
    else:
        cfg = dataclasses.replace(
            llama.LLAMA_BENCH, param_dtype=jnp.bfloat16, remat=True,
            attention_impl="flash",  # Pallas kernel on TPU (ops/pallas_attention)
            # fused lm-head CE kernel (ops/pallas_ce): interpret-mode
            # validated; flip on after one live-chip check
            ce_impl=(
                "fused"
                if os.environ.get("RAY_TPU_BENCH_FUSED_CE", "").lower()
                in ("1", "true", "yes")
                else "xla"
            ),
        )
        batch, seq, steps = 8, 2048, 10

    mesh = parallel.make_mesh(devices=jax.devices())
    opt = parallel.default_optimizer(1e-4, warmup_steps=10, total_steps=1000)
    state, state_sh = parallel.create_train_state(
        mesh, jax.random.PRNGKey(0),
        lambda r: llama.init_params(r, cfg), opt, llama.param_specs(cfg),
    )
    step = parallel.make_train_step(
        partial(llama.loss_fn, config=cfg), opt, mesh, state_sh
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size,
        dtype=jnp.int32,
    )
    batch_dict = {"tokens": tokens}

    # Warmup / compile.
    state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_dict)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    n_chips = len(jax.devices())
    tps_chip = tokens_per_sec / n_chips

    flops_tok = llama.flops_per_token(cfg, seq)
    achieved_tflops = tokens_per_sec * flops_tok / n_chips / 1e12
    peak = _detect_peak() if not on_cpu else 1.0
    mfu = achieved_tflops / peak

    print(json.dumps({
        "metric": "llama1b_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/s/chip",
        # top-level platform stamp (same contract as bench_core rows):
        # consumers comparing rows must check it before ratioing
        "platform": jax.devices()[0].platform,
        # the MFU baseline is accelerator-class hardware; a CPU
        # fallback's "MFU" (peak=1.0 placeholder) must not masquerade
        # as a ratio — refuse it, same contract as bench_core.report()
        "vs_baseline": None if on_cpu else round(mfu / 0.50, 3),
        "detail": {
            "model_params": llama.param_count(cfg),
            "batch": batch, "seq": seq, "steps": steps,
            "achieved_tflops_per_chip": round(achieved_tflops, 1),
            "mfu": round(mfu, 3),
            "n_chips": n_chips,
            "platform": jax.devices()[0].platform,
            "loss": round(float(metrics["loss"]), 4),
            **(
                {
                    "note": (
                        "CPU FALLBACK - TPU tunnel unreachable; number "
                        "not comparable to the TPU baseline. See "
                        "BENCH_NOTE.md for the last live-chip result."
                    )
                }
                if on_cpu
                else {}
            ),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
