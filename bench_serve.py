"""Serve-plane closed-loop load generator.

Companion to bench_core.py (same harness conventions, same JSON
shapes) for the serving data plane: closed-loop driver threads at
FIXED concurrency against a mix of a CPU microservice (2 replicas,
unbatched) and an LLM-stub (one replica, @serve.batch max_batch_size=8
with a per-BATCH simulated forward pass) measure sustained QPS,
request latency percentiles under the mixed load, and batch efficiency
(mean actual/max batch size) straight from the serve SLO registry
(`ray_tpu serve status` reads the same numbers). A final chaos row
re-runs the closed loop in a subprocess cluster with a
RAY_TPU_CHAOS_PLAN worker kill firing MID-LOAD and reports the
fraction of requests that still completed — the graceful-degradation
number the drain/reroute path is accountable for.

Closed-loop means each driver thread holds exactly one request in
flight (submit -> block on result -> repeat), so offered load adapts
to service rate and QPS is a throughput measure, not an arrival-rate
assumption. All rows are net-new (no reference analogue); baselines
were measured on this repo's CI box at the row's introduction (PR 13)
via `python bench_serve.py --trials 3` — see BENCH_serve_pr13.json.

Run: python bench_serve.py [--quick] [--smoke] [--trials N] [--json PATH]
(flags behave exactly as bench_core.py's; numbers from --smoke are NOT
comparable). Serial runs only — never concurrently with tier-1 or
bench_core (BENCH_NOTE.md).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time

import numpy as np

BASELINES = {
    # closed-loop req/s, 8 driver threads over 2 unbatched replicas
    "serve_micro_qps": 1063.0,
    # closed-loop req/s, 16 driver threads over the batched LLM stub
    # (one replica, max_batch_size=8, 4 ms simulated forward per batch)
    "serve_llm_stub_qps": 1236.0,
    # request latency under the MIXED load (both deployments driven at
    # once); LOWER is better (see _LOWER_IS_BETTER)
    "serve_mixed_p50_ms": 9.7,
    "serve_mixed_p99_ms": 21.8,
    # mean actual/max batch size over the llm-stub run, read from the
    # serve SLO registry (1.0 = every forward pass ran a full batch)
    "serve_batch_efficiency": 0.86,
    # fraction of closed-loop requests that completed while a chaos
    # plan SIGKILLed a worker mid-load (replica death -> handle reroute
    # + controller respawn); 1.0 = fully graceful degradation
    "serve_chaos_success_rate": 0.99,
    # payload sweep (PR 14): request p50 for an echo deployment moving
    # the SAME body both ways (request arg + response). 1 KiB and
    # 64 KiB ride the inline hub path (64 KiB == serve_inline_max, the
    # documented "inline still wins" boundary); 1 MiB and 8 MiB spill
    # onto the zero-copy object plane (serve/_private/payloads.py).
    # LOWER is better. Baselines measured at the rows' introduction
    # (PR 14, post-payload-plane) — see BENCH_serve_pr14_after.json.
    "serve_payload_1k_p50_ms": 1.45,
    "serve_payload_64k_p50_ms": 1.85,
    "serve_payload_1m_p50_ms": 5.0,
    "serve_payload_8m_p50_ms": 16.0,
    # multi-tenant blend (PR 14): LLM-stub + ViT-stub (spilled ndarray
    # bodies) + CPU micro driven at once through a SHARDED hub
    # (RAY_TPU_HUB_SHARDS=4) in a fresh subprocess cluster; total
    # completed req/s across all three tenants
    "serve_multitenant_qps": 480.0,
    # autoscale-under-chaos (PR 15): the multi-tenant blend in a fresh
    # subprocess cluster while the LLM tenant autoscales 1->3 under
    # load, a priority gang preempts the co-tenant batch-training PG,
    # and a seeded serve chaos plan fires replica_kill + route_partition
    # + slow_replica faults. Success rate is over NON-SHED requests
    # (sheds are the admission controller doing its job and are asserted
    # fast separately); the row runs TWICE per measurement and asserts
    # both runs produce the identical fault sequence.
    "serve_autoscale_chaos_success_rate": 0.99,
    # p99 of successful request latency during the same chaos run;
    # LOWER is better — the "bounded tail under faults" number
    "serve_autoscale_chaos_p99_ms": 85.0,
    # shed fast-path: p50 latency of a synchronous admission-control
    # reject (RequestShedError out of handle.remote() past the
    # max_queued_requests cap) while the deployment is saturated.
    # LOWER is better — a shed must cost microseconds, not a timeout.
    "serve_shed_reject_p50_ms": 0.2,
}

_LOWER_IS_BETTER = {
    "serve_mixed_p50_ms",
    "serve_mixed_p99_ms",
    "serve_payload_1k_p50_ms",
    "serve_payload_64k_p50_ms",
    "serve_payload_1m_p50_ms",
    "serve_payload_8m_p50_ms",
    "serve_autoscale_chaos_p99_ms",
    "serve_shed_reject_p50_ms",
}

SMOKE = False
QUICK = False
TRIALS = None
JSON_PATH = None
RESULTS = []


def _parse_argv(argv) -> None:
    """Flag parsing stays out of import time (tests import this module
    for BASELINES; see bench_core._parse_argv)."""
    global SMOKE, QUICK, TRIALS, JSON_PATH
    SMOKE = "--smoke" in argv
    QUICK = "--quick" in argv or SMOKE
    if "--trials" in argv:
        try:
            TRIALS = int(argv[argv.index("--trials") + 1])
        except (IndexError, ValueError):
            sys.exit("--trials requires an integer argument")
        if TRIALS < 1:
            sys.exit("--trials must be >= 1")
    if "--json" in argv:
        try:
            JSON_PATH = argv[argv.index("--json") + 1]
        except IndexError:
            sys.exit("--json requires a path argument")
        if JSON_PATH.startswith("-"):
            sys.exit(
                f"--json requires a path argument, got flag {JSON_PATH!r}"
            )


def report(metric: str, value, unit: str) -> None:
    from bench_core import BASELINE_PLATFORM, _detect_platform

    trials_list = None
    if isinstance(value, list):  # --trials mode: per-trial samples
        trials_list = [round(v, 3) for v in value]
        value = float(np.median(value))
    platform = _detect_platform()
    base = BASELINES.get(metric)
    if platform != BASELINE_PLATFORM:
        # BASELINES are cpu-box numbers (bench_core contract): a row
        # measured on other hardware is stamped but never ratioed
        ratio = None
    elif base and metric in _LOWER_IS_BETTER:
        ratio = base / value
    elif base:
        ratio = value / base
    else:
        ratio = None
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "platform": platform,
        "vs_baseline": round(ratio, 3) if ratio else None,
    }
    if trials_list is not None:
        rec["trials"] = trials_list
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def _closed_loop(handle, concurrency: int, per_thread: int, payload):
    """Drive `concurrency` threads, each keeping exactly ONE request in
    flight for `per_thread` iterations. Returns (latencies_s, wall_s,
    errors)."""
    lats: list = []
    errors = [0]
    lock = threading.Lock()

    def work(k: int):
        mine = []
        for i in range(per_thread):
            t0 = time.perf_counter()
            try:
                handle.remote(payload(k, i)).result(timeout_s=60)
                mine.append(time.perf_counter() - t0)
            except Exception:
                with lock:
                    errors[0] += 1
        with lock:
            lats.extend(mine)

    threads = [
        threading.Thread(target=work, args=(k,)) for k in range(concurrency)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats, time.perf_counter() - t0, errors[0]


def _pctl(sorted_lats, p: float) -> float:
    return sorted_lats[
        min(len(sorted_lats) - 1, int(round(p / 100.0 * (len(sorted_lats) - 1))))
    ]


def main() -> None:
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, max_workers=4 if SMOKE else 8)

    @serve.deployment(num_replicas=2, max_ongoing_requests=16)
    class Micro:
        """CPU microservice: tiny deserialize-compute-reply round."""

        def __call__(self, x):
            return {"ok": x * 2}

    @serve.deployment(max_ongoing_requests=64)
    class LLMStub:
        """LLM-shaped service: requests coalesce into batches and pay
        one fixed 4 ms 'forward pass' PER BATCH, so throughput scales
        with batch efficiency, exactly like a real model server."""

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.003)
        async def generate(self, prompts):
            await asyncio.sleep(0.004)
            return ["gen:" + p for p in prompts]

        async def __call__(self, prompt):
            return await self.generate(prompt)

    micro = serve.run(Micro.bind())
    llm = serve.run(LLMStub.bind())

    CONC_MICRO = 4 if SMOKE else 8
    CONC_LLM = 8 if SMOKE else 16
    PER_THREAD = 5 if SMOKE else (25 if QUICK else 100)

    # warm both paths (replica spawn + first-route refresh)
    assert micro.remote(1).result(timeout_s=60) == {"ok": 2}
    assert llm.remote("w").result(timeout_s=60) == "gen:w"

    def micro_loop():
        lats, _, errs = _closed_loop(
            micro, CONC_MICRO, PER_THREAD, lambda k, i: i
        )
        assert errs == 0, f"{errs} micro requests failed"
        return len(lats)

    report("serve_micro_qps", _timeit(micro_loop), "req/s")

    def llm_loop():
        lats, _, errs = _closed_loop(
            llm, CONC_LLM, PER_THREAD, lambda k, i: f"p{k}-{i}"
        )
        assert errs == 0, f"{errs} llm requests failed"
        return len(lats)

    report("serve_llm_stub_qps", _timeit(llm_loop), "req/s")

    # ---- mixed load: both deployments driven at once; percentiles are
    # over ALL requests, so they price cross-service interference
    def mixed_once():
        out = {}

        def drive(name, handle, conc, payload):
            out[name] = _closed_loop(handle, conc, PER_THREAD, payload)

        gm = threading.Thread(
            target=drive, args=("m", micro, CONC_MICRO // 2, lambda k, i: i)
        )
        gl = threading.Thread(
            target=drive,
            args=("l", llm, CONC_LLM // 2, lambda k, i: f"m{k}-{i}"),
        )
        gm.start(); gl.start(); gm.join(); gl.join()
        lats = sorted(out["m"][0] + out["l"][0])
        assert lats, "mixed run completed no requests"
        return _pctl(lats, 50) * 1e3, _pctl(lats, 99) * 1e3

    mixed = [mixed_once() for _ in range(TRIALS or 1)]
    report(
        "serve_mixed_p50_ms",
        [m[0] for m in mixed] if TRIALS else mixed[0][0], "ms",
    )
    report(
        "serve_mixed_p99_ms",
        [m[1] for m in mixed] if TRIALS else mixed[0][1], "ms",
    )

    # ---- payload sweep: one echo deployment moving the same body BOTH
    # ways per request. Above serve_inline_max (64 KiB) the body spills
    # onto the zero-copy object plane: the handle puts it as a shm
    # segment and the replica maps it back as a memoryview; at or below
    # the threshold it rides VAL_INLINE through the hub — the 1 KiB and
    # 64 KiB rows price the "inline still wins" boundary, 1 MiB / 8 MiB
    # price the spill path the plane exists for.
    @serve.deployment(max_ongoing_requests=16)
    class PayloadEcho:
        def __call__(self, x):
            return x

    echo = serve.run(PayloadEcho.bind())
    warm = echo.remote(b"w" * 2048).result(timeout_s=60)
    assert bytes(warm) == b"w" * 2048

    sweep = [
        ("serve_payload_1k_p50_ms", 1024, 100),
        ("serve_payload_64k_p50_ms", 64 * 1024, 60),
        ("serve_payload_1m_p50_ms", 1024 * 1024, 30),
        ("serve_payload_8m_p50_ms", 8 * 1024 * 1024, 8),
    ]
    for metric, size, iters in sweep:
        n = 3 if SMOKE else (max(4, iters // 2) if QUICK else iters)
        body = b"\xa5" * size
        # spill warm-up at this size (resolve cache, segment pool)
        assert echo.remote(body).result(timeout_s=60) is not None

        def payload_once(body=body, n=n):
            lats, _, errs = _closed_loop(echo, 2, n, lambda k, i: body)
            assert errs == 0, f"{errs} payload requests failed"
            return _pctl(sorted(lats), 50) * 1e3

        vals = [payload_once() for _ in range(TRIALS or 1)]
        report(metric, vals if TRIALS else vals[0], "ms")

    # ---- batch efficiency from the SLO registry (cumulative over the
    # llm + mixed runs above — the same number `serve status` renders)
    from ray_tpu.util import state as state_api

    eff = None
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        dep = state_api.summarize_serve()["deployments"].get("LLMStub")
        if dep and dep.get("batch_efficiency") is not None:
            eff = dep["batch_efficiency"]
            break
        time.sleep(0.1)
    assert eff is not None, "LLMStub batch_efficiency never landed"
    report("serve_batch_efficiency", eff, "ratio")

    # ---- shed fast-path: saturate a capped deployment, then price the
    # synchronous admission reject. A shed must never queue into a
    # timeout — it fails at .remote(), before payload spill or replica
    # wait, so the whole cost is one outstanding-count reconcile.
    @serve.deployment(max_ongoing_requests=2, max_queued_requests=4)
    class Capped:
        def __call__(self, s):
            time.sleep(s)
            return s

    capped = serve.run(Capped.bind())
    assert capped.remote(0).result(timeout_s=60) == 0

    from ray_tpu.exceptions import RequestShedError

    def shed_once():
        hold_s = 0.6 if SMOKE else 1.2
        admitted = []
        # fill the queue to the cap (the holders keep the replica busy
        # well past the measurement window)
        while True:
            try:
                admitted.append(capped.remote(hold_s))
            except RequestShedError:
                break
        rejects = []
        stop = time.perf_counter() + hold_s * 0.6
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            try:
                capped.remote(0)
            except RequestShedError:
                rejects.append(time.perf_counter() - t0)
        for r in admitted:  # drain so the next trial starts empty
            r.result(timeout_s=60)
        assert rejects, "saturated deployment never shed"
        return _pctl(sorted(rejects), 50) * 1e3

    shed_vals = [shed_once() for _ in range(TRIALS or 1)]
    report(
        "serve_shed_reject_p50_ms",
        shed_vals if TRIALS else shed_vals[0], "ms",
    )

    serve.shutdown()
    ray_tpu.shutdown()

    # ---- multi-tenant blend through a SHARDED hub: fresh subprocess
    # cluster (RAY_TPU_HUB_SHARDS is read at hub init) so the row
    # prices the realistic topology — several tenants, reactor shards,
    # spilled ndarray bodies on the ViT path
    _bench_multitenant()

    # ---- chaos: fresh subprocess cluster (the plan is read at hub
    # init) with a worker SIGKILL firing mid-load
    _bench_chaos_degradation()

    # ---- the PR 15 measured run: multi-tenant blend + autoscaling +
    # priority gang preemption + seeded serve-scope faults, twice per
    # measurement to prove the fault sequence is deterministic
    _bench_autoscale_chaos()

    from bench_core import BASELINE_PLATFORM, _detect_platform

    # geomean only over baseline-platform rows (off-platform rows carry
    # vs_baseline=None by construction — same filter as bench_core)
    ratios = [r["vs_baseline"] for r in RESULTS
              if r["vs_baseline"] and r.get("platform") == BASELINE_PLATFORM]
    geomean = float(np.exp(np.mean(np.log(ratios)))) if ratios else 0.0
    summary = {
        "metric": "serve_bench_geomean_vs_baseline",
        "value": round(geomean, 3),
        "unit": "ratio",
        "platform": _detect_platform(),
        "vs_baseline": round(geomean, 3),
        "detail": {r["metric"]: r["value"] for r in RESULTS},
    }
    print(json.dumps(summary))
    if JSON_PATH:
        with open(JSON_PATH, "w") as f:
            json.dump(
                {
                    "mode": "smoke" if SMOKE else ("quick" if QUICK else "full"),
                    "trials": TRIALS or 1,
                    "platform": _detect_platform(),
                    "metrics": {r["metric"]: r for r in RESULTS},
                    "geomean_vs_baseline": round(geomean, 3),
                },
                f, indent=2,
            )
            f.write("\n")


def _timeit(fn):
    """req/s from fn() -> completed count; median-of-TRIALS samples or
    best-of-trials, mirroring bench_core.timeit (warmup already done by
    the explicit warm requests in main)."""
    if TRIALS:
        samples = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            n = fn()
            samples.append(n / (time.perf_counter() - t0))
        return samples
    best = 0.0
    for _ in range(1 if QUICK else 3):
        t0 = time.perf_counter()
        n = fn()
        best = max(best, n / (time.perf_counter() - t0))
    return best


def _multitenant_qps(per_thread: int) -> float:
    """One subprocess cluster with RAY_TPU_HUB_SHARDS=4 hosting three
    tenants at once — an LLM stub (@serve.batch over string prompts),
    a ViT stub (@serve.batch over 224x224x3 float32 frames, ~600 KiB
    each, so every request body rides the zero-copy payload plane) and
    a CPU microservice — driven concurrently closed-loop. Returns total
    completed requests / wall second across all tenants."""
    import subprocess

    script = f"""
import sys; sys.path.insert(0, {json.dumps(os.path.dirname(os.path.abspath(__file__)))})
import asyncio, threading, time
import numpy as np
import ray_tpu
from ray_tpu import serve

ray_tpu.init(num_cpus=8, max_workers=6)

@serve.deployment(num_replicas=2, max_ongoing_requests=16)
class Micro:
    def __call__(self, x):
        return {{"ok": x * 2}}

@serve.deployment(max_ongoing_requests=64)
class LLMStub:
    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.003)
    async def generate(self, prompts):
        await asyncio.sleep(0.004)
        return ["gen:" + p for p in prompts]
    async def __call__(self, prompt):
        return await self.generate(prompt)

@serve.deployment(max_ongoing_requests=32)
class ViTStub:
    # the batch callable IS the routed target: all spilled frames in a
    # batch resolve through ONE shared payload fetch (payloads.py)
    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.004)
    async def __call__(self, frames):
        await asyncio.sleep(0.003)  # one simulated forward per BATCH
        return [float(f[0][0][0]) if hasattr(f, "shape") else float(np.asarray(f)[0,0,0]) for f in frames]

micro = serve.run(Micro.bind())
llm = serve.run(LLMStub.bind())
vit = serve.run(ViTStub.bind())
frame = np.full((224, 224, 3), 0.5, dtype=np.float32)  # ~600 KiB: spills
assert micro.remote(1).result(timeout_s=60) == {{"ok": 2}}
assert llm.remote("w").result(timeout_s=60) == "gen:w"
assert vit.remote(frame).result(timeout_s=60) == 0.5

done = [0]
lock = threading.Lock()

def drive(handle, n, payload):
    for i in range(n):
        handle.remote(payload(i)).result(timeout_s=60)
        with lock:
            done[0] += 1

N = {per_thread}
jobs = (
    [(micro, N, lambda i: i)] * 3
    + [(llm, N, lambda i: f"p{{i}}")] * 3
    + [(vit, max(2, N // 4), lambda i: frame)] * 2
)
threads = [threading.Thread(target=drive, args=j) for j in jobs]
t0 = time.perf_counter()
for t in threads: t.start()
for t in threads: t.join()
print("QPS", done[0] / (time.perf_counter() - t0))
serve.shutdown()
ray_tpu.shutdown()
"""
    env = {**os.environ, "RAY_TPU_HUB_SHARDS": "4"}
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True,
        text=True, timeout=300, env=env,
    )
    qps = next(
        (float(line.split()[1]) for line in out.stdout.splitlines()
         if line.startswith("QPS")),
        None,
    )
    if qps is None:
        raise RuntimeError(
            f"multitenant subprocess rc={out.returncode}: "
            f"{(out.stderr or out.stdout)[-400:]}"
        )
    return qps


def _bench_multitenant() -> None:
    per_thread = 5 if SMOKE else (25 if QUICK else 80)
    samples = []
    for _ in range(TRIALS or 1):
        for attempt in range(3):  # same retry story as the chaos row
            try:
                samples.append(_multitenant_qps(per_thread))
                break
            except Exception as e:  # noqa: BLE001
                if attempt == 2:
                    raise
                print(
                    f"serve_multitenant trial retry after: {e}",
                    file=sys.stderr,
                )
    report(
        "serve_multitenant_qps",
        samples if TRIALS else samples[0], "req/s",
    )


def _chaos_success_rate(duration_s: float, kill_at_s: float) -> float:
    """One subprocess cluster driving the closed loop while the chaos
    plan SIGKILLs a worker at kill_at_s; returns completed/attempted.
    Victims are seeded-random among live workers, so across trials the
    kill lands on a replica (handle reroute + controller respawn) or on
    the controller/an idle worker — both are production faults the
    serve plane must absorb."""
    import subprocess

    script = f"""
import sys; sys.path.insert(0, {json.dumps(os.path.dirname(os.path.abspath(__file__)))})
import threading, time
import ray_tpu
from ray_tpu import serve

ray_tpu.init(num_cpus=4, max_workers=4)

@serve.deployment(num_replicas=2, max_ongoing_requests=16)
class Hit:
    def __call__(self, x):
        time.sleep(0.005)
        return x

# deploy + warm under the live chaos plan: the timed kill can land on
# a replica DURING readiness (deploy is ~1s on a loaded box, the same
# order as kill_at_s) — that is a survived fault too, so redeploy and
# re-warm instead of dying before the measured load window opens
for _attempt in range(5):
    try:
        handle = serve.run(Hit.bind())
        assert handle.remote(0).result(timeout_s=60) == 0  # warm
        break
    except Exception as e:
        print("deploy retry after:", type(e).__name__, file=sys.stderr)
        time.sleep(0.5)
else:
    raise SystemExit("Hit deployment never became ready under chaos")
stop_at = time.monotonic() + {duration_s}
succ, total = [0], [0]
lock = threading.Lock()

def work():
    while time.monotonic() < stop_at:
        with lock:
            total[0] += 1
        try:
            handle.remote(1).result(timeout_s=30)
            with lock:
                succ[0] += 1
        except Exception:
            pass

threads = [threading.Thread(target=work) for _ in range(4)]
for t in threads: t.start()
for t in threads: t.join()
print("RATE", succ[0] / max(1, total[0]), succ[0], total[0])
serve.shutdown()
ray_tpu.shutdown()
"""
    env = {
        **os.environ,
        "RAY_TPU_CHAOS_PLAN": f"seed=7;worker_kill:1@{kill_at_s}s",
    }
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True,
        text=True, timeout=300, env=env,
    )
    rate = next(
        (float(line.split()[1]) for line in out.stdout.splitlines()
         if line.startswith("RATE")),
        None,
    )
    if rate is None:
        raise RuntimeError(
            f"chaos subprocess rc={out.returncode}: "
            f"{(out.stderr or out.stdout)[-400:]}"
        )
    return rate


def _bench_chaos_degradation() -> None:
    duration = 2.5 if SMOKE else (3.5 if QUICK else 5.0)
    kill_at = 1.0 if SMOKE else 1.5
    samples = []
    for _ in range(TRIALS or 1):
        # a chaos trial races replica spawn against the timed kill on a
        # possibly loaded box: retry transient setup failures rather
        # than silently dropping the row (the harness-coverage test
        # requires every BASELINES row), and fail LOUDLY when the
        # degradation path is actually broken
        for attempt in range(3):
            try:
                samples.append(_chaos_success_rate(duration, kill_at))
                break
            except Exception as e:  # noqa: BLE001
                if attempt == 2:
                    raise
                print(
                    f"serve_chaos trial retry after: {e}", file=sys.stderr
                )
    report(
        "serve_chaos_success_rate",
        samples if TRIALS else samples[0], "ratio",
    )


_AUTOSCALE_CHAOS_PLAN = (
    "seed=7;replica_kill:Micro@1.2s;replica_kill:ViT@2.2s;"
    "route_partition:LLM@1s-2.5s;slow_replica:Micro@1ms-5ms@0.2"
)


def _autoscale_chaos_run(duration_s: float) -> dict:
    """One subprocess cluster running the measured autoscale-under-chaos
    blend: the LLM tenant autoscales 1->3 under closed-loop load, a
    low-priority batch-training gang holds spare CPU until a
    higher-priority gang preempts it mid-run (fairsched PR 5), and the
    seeded serve chaos plan kills replicas, blackholes the LLM handle's
    routing refresh, and injects Micro execute latency. Returns the
    parsed result dict, including the deterministic fault sequence read
    from the controller's chaos snapshot."""
    import subprocess

    script = f"""
import sys; sys.path.insert(0, {json.dumps(os.path.dirname(os.path.abspath(__file__)))})
import asyncio, json, threading, time
import numpy as np
import ray_tpu
from ray_tpu import serve
from ray_tpu.exceptions import RequestShedError
from ray_tpu.util.placement_group import placement_group, remove_placement_group

ray_tpu.init(num_cpus=8, max_workers=10)

@serve.deployment(autoscaling_config={{"min_replicas": 1, "max_replicas": 3,
                                       "target_ongoing_requests": 2}},
                  max_ongoing_requests=32)
class LLM:
    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.003)
    async def gen(self, prompts):
        await asyncio.sleep(0.004)
        return ["gen:" + p for p in prompts]
    async def __call__(self, p):
        return await self.gen(p)

@serve.deployment(max_ongoing_requests=32)
class ViT:
    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.004)
    async def __call__(self, frames):
        await asyncio.sleep(0.003)
        return [float(np.asarray(f)[0, 0, 0]) for f in frames]

@serve.deployment(num_replicas=2, max_ongoing_requests=4,
                  max_queued_requests=24)
class Micro:
    def __call__(self, x):
        time.sleep(0.002)
        return x

llm = serve.run(LLM.bind())
vit = serve.run(ViT.bind())
micro = serve.run(Micro.bind())
frame = np.full((64, 64, 3), 0.5, dtype=np.float32)
assert llm.remote("w").result(timeout_s=60) == "gen:w"
assert vit.remote(frame).result(timeout_s=60) == 0.5
assert micro.remote(0).result(timeout_s=60) == 0

# co-tenant: a low-priority batch-training gang parks on the spare CPU
filler = placement_group([{{"CPU": 6.0}}], priority=-10, tenant="batch-train")
assert filler.wait(10), "batch-training gang never placed"

stop_at = time.monotonic() + {duration_s}
lock = threading.Lock()
stats = {{"ok": 0, "fail": 0, "shed": 0, "shed_slow": 0}}
lats = []

def work(handle, payload):
    h = handle.options(request_timeout_s=10.0)
    while time.monotonic() < stop_at:
        t0 = time.perf_counter()
        try:
            h.remote(payload()).result()
            dt = time.perf_counter() - t0
            with lock:
                stats["ok"] += 1
                lats.append(dt)
        except RequestShedError:
            # the overload controller refusing work IS correct behavior
            # under this blend; what matters is that the reject is fast
            dt = time.perf_counter() - t0
            with lock:
                stats["shed"] += 1
                if dt > 0.5:
                    stats["shed_slow"] += 1
            time.sleep(0.001)
        except Exception:
            with lock:
                stats["fail"] += 1

jobs = ([(llm, lambda: "p")] * 6 + [(vit, lambda: frame)] * 2
        + [(micro, lambda: 1)] * 6)
threads = [threading.Thread(target=work, args=j) for j in jobs]
for t in threads: t.start()

# autoscale observation rides the drive (instantaneous ongoing samples
# oscillate by design, so track the high-water mark, not the endpoint)
ctrl = ray_tpu.get_actor("__serve_controller")
max_llm = 1
def watch():
    global max_llm
    while time.monotonic() < stop_at:
        try:
            deps = ray_tpu.get(ctrl.list_deployments.remote(), timeout=5)
            max_llm = max(max_llm, deps["LLM"]["live_replicas"])
        except Exception:
            pass
        time.sleep(0.1)
w = threading.Thread(target=watch)
w.start()

# mid-run: an urgent gang arrives; fairsched preempts the
# strictly-lower-priority batch-training gang to seat it
time.sleep(min(2.6, {duration_s} * 0.7))
urgent = placement_group([{{"CPU": 6.0}}], priority=5, tenant="urgent")
preempted = urgent.wait(15)

for t in threads: t.join()
w.join()
assert preempted, "urgent gang was never seated (preemption failed)"
for pg in (urgent, filler):
    try:
        remove_placement_group(pg)
    except Exception:
        pass

snap = ray_tpu.get(ctrl.chaos_snapshot.remote())
seq = [[e["kind"], e.get("deployment"), e.get("victim_index"), e.get("at_s")]
       for e in snap.get("events", []) if e["kind"] == "replica_kill"]
lats.sort()
p99_ms = lats[int(0.99 * (len(lats) - 1))] * 1e3 if lats else -1.0
total = stats["ok"] + stats["fail"]
out = {{
    "rate": stats["ok"] / max(1, total),
    "p99_ms": p99_ms,
    "max_lat_s": lats[-1] if lats else 0.0,
    "shed": stats["shed"], "shed_slow": stats["shed_slow"],
    "max_llm_replicas": max_llm,
    "fault_seq": seq,
    "route_partitions": snap.get("route_partitions", {{}}),
}}
print("RESULT " + json.dumps(out))
serve.shutdown()
ray_tpu.shutdown()
"""
    env = {
        **os.environ,
        "RAY_TPU_CHAOS_PLAN": _AUTOSCALE_CHAOS_PLAN,
        # give the transparent retry enough backoff runway to outlast a
        # controller respawn of a killed single-replica deployment
        "RAY_TPU_SERVE_RETRY_ATTEMPTS": "6",
    }
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True,
        text=True, timeout=300, env=env,
    )
    res = next(
        (json.loads(line[len("RESULT "):])
         for line in out.stdout.splitlines() if line.startswith("RESULT")),
        None,
    )
    if res is None:
        raise RuntimeError(
            f"autoscale-chaos subprocess rc={out.returncode}: "
            f"{(out.stderr or out.stdout)[-600:]}"
        )
    return res


def _bench_autoscale_chaos() -> None:
    duration = 3.5 if SMOKE else (4.5 if QUICK else 6.0)
    rates, p99s = [], []
    for _ in range(TRIALS or 1):
        for attempt in range(3):
            try:
                # TWO runs per measurement: same seed -> the fault
                # sequence (victim draws, kill ticks, partition windows)
                # must be bit-identical; numbers come from the first
                a = _autoscale_chaos_run(duration)
                b = _autoscale_chaos_run(duration)
                break
            except Exception as e:  # noqa: BLE001
                if attempt == 2:
                    raise
                print(
                    f"serve_autoscale_chaos trial retry after: {e}",
                    file=sys.stderr,
                )
        assert a["fault_seq"] == b["fault_seq"], (
            "same seed, different fault sequence:\n"
            f"  run A: {a['fault_seq']}\n  run B: {b['fault_seq']}"
        )
        assert a["route_partitions"] == b["route_partitions"]
        assert a["fault_seq"], "no replica_kill fault ever fired"
        # the acceptance floor: non-shed success rate, fast sheds, no
        # request outliving its deadline, and a real scale-up under load
        assert a["rate"] >= 0.99, f"success rate {a['rate']:.4f} < 0.99"
        assert a["shed_slow"] == 0, (
            f"{a['shed_slow']} shed rejects took > 0.5s (must fail fast)"
        )
        assert a["max_lat_s"] < 12.0, (
            f"a request outlived its 10s deadline: {a['max_lat_s']:.1f}s"
        )
        assert a["max_llm_replicas"] >= 2, (
            "LLM tenant never scaled past 1 replica under load"
        )
        rates.append(a["rate"])
        p99s.append(a["p99_ms"])
    report(
        "serve_autoscale_chaos_success_rate",
        rates if TRIALS else rates[0], "ratio",
    )
    report(
        "serve_autoscale_chaos_p99_ms",
        p99s if TRIALS else p99s[0], "ms",
    )


if __name__ == "__main__":
    _parse_argv(sys.argv[1:])
    main()
